"""Feature gates — runtime on/off switches for graduated features.

Reference: ``pkg/features/kube_features.go`` + the map-typed
``--feature-gates`` flag (``staging/.../util/feature/feature_gate.go``).
The fork's signature move was flipping ``DevicePlugins`` to Beta/true
(``kube_features.go:252``); the TPU build's device path is GA from
birth, so the gated surface here is the newer operational machinery.

Usage::

    from kubernetes_tpu.util.features import GATES
    if GATES.enabled("NodePressureEviction"): ...

Components read the process-global ``GATES``; tests may build a private
``FeatureGates(overrides=...)`` and inject it.
"""
from __future__ import annotations

from dataclasses import dataclass

ALPHA = "Alpha"
BETA = "Beta"
GA = "GA"


@dataclass(frozen=True)
class Feature:
    name: str
    default: bool
    stage: str
    description: str = ""


#: The gate table (reference: kube_features.go's known-features map).
KNOWN_FEATURES = {f.name: f for f in [
    Feature("TpuDevicePlugins", True, GA,
            "device-plugin seam for TPU chips (fork: DevicePlugins beta)"),
    Feature("GangScheduling", True, GA,
            "all-or-nothing PodGroup placement"),
    Feature("SubMeshAllocation", True, GA,
            "contiguous ICI sub-mesh allocation for slice_shape claims"),
    Feature("PodPriority", True, BETA,
            "priority-based scheduler preemption + kubelet critical-pod "
            "admission preemption (reference: PodPriority beta)"),
    Feature("NodePressureEviction", True, BETA,
            "memory/disk-pressure pod eviction on the node agent"),
    Feature("ServiceProxy", True, BETA,
            "per-node userspace VIP forwarder + service env injection"),
    Feature("PodUidIsolation", False, ALPHA,
            "per-pod uid/gid allocation + private volume dirs on "
            "privileged (root) node agents; pods cannot read each "
            "other's files"),
    Feature("IptablesProxier", False, ALPHA,
            "kernel NAT service dataplane: render + iptables-restore "
            "rulesets from Services/Endpoints (needs root; userspace "
            "proxy stays on as fallback)"),
    Feature("NetworkPolicy", False, ALPHA,
            "NetworkPolicy enforcement: render + apply per-pod "
            "iptables filter chains (needs root; rulesets are computed "
            "and testable either way)"),
    Feature("IpvsProxier", False, ALPHA,
            "IPVS kernel dataplane: virtual servers per service port, "
            "incremental ipvsadm deltas + ipset-driven static iptables "
            "(needs root+ipvsadm; userspace proxy stays on as "
            "fallback; mutually exclusive with IptablesProxier)"),
    Feature("NativeSubmeshFastPath", True, BETA,
            "C++ sub-mesh search fast path (falls back to numpy)"),
    Feature("AuditLogging", True, BETA,
            "structured request audit capability; actual logging still "
            "requires an --audit-log path"),
    Feature("JobQueueing", False, ALPHA,
            "multi-tenant fair-share admission for gang jobs: "
            "ClusterQueue/LocalQueue quotas, DRF ordering, cohort "
            "borrowing with gang-aware reclaim, and backfill "
            "(queueing/ + controllers/queue.py); off = PodGroups "
            "race straight into the scheduling queue as before"),
    Feature("SchedulerLeaderElection", False, ALPHA,
            "active-standby scheduler: N scheduler processes elect one "
            "active instance via a Lease (scheduler.ElectedScheduler); "
            "standbys keep informers warm and take over on leader "
            "stop/crash — two schedulers can never double-bind. Off = "
            "the scheduler runs unconditionally, as before"),
    Feature("ApiServerSharding", False, ALPHA,
            "resource-group sharded apiserver workers: non-watch "
            "resource requests dispatch to per-group worker loops "
            "(pods/bindings, nodes, queueing, events) over the shared "
            "MVCC/WAL, behind a router that keeps the URL surface and "
            "watch semantics byte-identical (apiserver/sharding.py). "
            "Off = every request runs on the single router loop, "
            "byte-identical to the unsharded apiserver"),
    Feature("ApiServerCodecOffload", False, ALPHA,
            "process-pool JSON codec offload: encode-cache misses on "
            "large LIST assembly and decode of large request bodies "
            "dispatch to a concurrent.futures pool behind the "
            "serialize-once cache (apiserver/codecpool.py), with a "
            "size threshold so small objects stay inline; on hosts "
            "without spare cores the pool stays inline (metric-"
            "visible). Off = all codec work runs on the event loop, "
            "byte-identical"),
    Feature("GracefulPreemption", False, ALPHA,
            "checkpoint-aware gang preemption (preemption.py): signal "
            "the gang (SIGTERM + KTPU_PREEMPT file), wait bounded by "
            "spec.checkpoint.grace_seconds for checkpoint-complete "
            "markers, then requeue with resume state; elastic gangs "
            "shrink to spec.min_replicas under reclaim instead of "
            "dying. Off = every eviction path is the legacy hard "
            "kill, byte-identical"),
    Feature("InferenceAutoscaling", False, ALPHA,
            "autoscaled inference serving (serving/v1 InferenceService, "
            "controllers/inference.py): reconcile model-server pods via "
            "a headless Service + Deployment, and an HPA-analog loop "
            "scaling replicas on ClusterMonitor.latest() rollups with "
            "stabilization windows and rate limits; warm-pool image "
            "pre-pull ahead of the first scale-up. Off = the controller "
            "and the admission defaulter are inert, byte-identical"),
    Feature("ServingTopologyAware", False, ALPHA,
            "slice-topology-aware serving placement/routing: the "
            "scheduler scores serving-labeled pods by how little their "
            "chip claim shrinks the slice's largest free contiguous "
            "box (large training gangs keep their sub-meshes), and the "
            "endpoint router prefers same-slice/least-fragmented "
            "replicas. Off = legacy placement, byte-identical"),
    Feature("ClusterMetricsPipeline", False, ALPHA,
            "kmon Prometheus-analog metrics pipeline (monitoring/"
            "pipeline.py): scrape manager over apiserver + component + "
            "node /metrics endpoints, bounded in-memory TSDB, "
            "PromQL-lite /debug/v1/query surface (ktl query|alerts|"
            "dash), and recording/alerting rules whose verdicts become "
            "Events. Off = no scrape traffic, no TSDB, no metrics "
            "listeners, /debug/v1/query answers 404 — byte-identical"),
    Feature("AlertNodeTainting", False, ALPHA,
            "kmon alert-driven node tainting: firing node-degrading "
            "alerts (sick chip, duty collapse, ICI stall) add a "
            "tpu.google.com/degraded NoSchedule taint, removed when "
            "the node's last degrading alert resolves — the seam a "
            "migration/defrag controller consumes. Requires "
            "ClusterMetricsPipeline; off = alerts record Events only"),
    Feature("GangLiveMigration", False, ALPHA,
            "live gang migration + defragmentation (controllers/"
            "migrate.py): reserve-then-move — CAS a target contiguous "
            "sub-mesh reservation in the scheduler cache FIRST, then "
            "checkpoint-migrate the gang through the graceful "
            "preemption engine onto the reserved box; triggers are "
            "tpu.google.com/degraded taints (evacuate sick chips "
            "before they fail) and a defrag planner scoring moves by "
            "the gain in largest_free_box_volume, under a budget "
            "(max concurrent rounds, per-gang cooldown). Requires "
            "GracefulPreemption for actual moves. Off = no watches, "
            "no reservations, no status writes — byte-identical"),
    Feature("SchedulerFastPath", False, ALPHA,
            "columnar scheduler hot path (scheduler/fleetarray.py): a "
            "numpy fleet snapshot maintained incrementally from cache "
            "events; feasibility filtering and priority scoring for "
            "eligible pods (and whole drained queue batches) run as "
            "vectorized array ops instead of per-node Python loops, "
            "with exact scalar fallback for pods needing affinity/"
            "policy/extenders/reservations. Placement decisions are "
            "identical to the scalar path by construction (property-"
            "tested); off = the per-pod scalar loop, byte-identical"),
    Feature("CompactWireCodec", False, ALPHA,
            "compact framed wire codec for the full wire path "
            "(util/compactcodec.py): LIST responses, watch streams, "
            "AND the write path — CREATE / {plural}:batchCreate / "
            "bindings:batch request bodies negotiated via "
            "Content-Type, batch responses via Accept — as "
            "length-prefixed msgpack frames on top of the "
            "serialize-once encode cache; JSON remains the default "
            "and the fallback (a client that never asks, or a server "
            "with the gate off, sees byte-identical JSON). Requires "
            "the msgpack wheel; without it the gate is inert"),
    Feature("WatchFanoutBatch", False, ALPHA,
            "watch fan-out flush batching (apiserver/fanout.py): "
            "watch handlers append encoded event frames to "
            "per-watcher sinks; a small pool of flusher workers — "
            "watchers sharded across them — coalesces each sink's "
            "pending frames into one buffered writev-style socket "
            "send per flush round, so a slow consumer stalls only "
            "its own shard's round and an overflowing one is closed "
            "(the client relists). Off = the per-watcher inline "
            "write loop, byte-identical"),
    Feature("TrainJobController", False, ALPHA,
            "multi-host jax.distributed training as a first-class "
            "workload (training/v1 TrainJob, controllers/train.py): "
            "reconcile a TrainJob into a headless Service + a "
            "gang-annotated indexed worker pod set running "
            "workloads/trainer.py, where every rank discovers the "
            "rank-0 coordinator through workloads/rendezvous.py and "
            "the cluster's own DNS; gang recovery rounds on member "
            "failure with Orbax resume from the shared checkpoint "
            "volume. Off = the controller is inert, byte-identical"),
    Feature("WatchBookmarks", False, ALPHA,
            "periodic watch bookmark frames under traffic (reference: "
            "WatchBookmark): the apiserver injects BOOKMARK events "
            "carrying the current store revision into every watch "
            "stream (JSON and compact codec) about once per bookmark "
            "interval, and SharedInformer reconnects resume from the "
            "last bookmark instead of a full LIST+decode; a resume "
            "below the store's compacted floor still gets 410 Gone "
            "and falls back to relist. The pre-existing idle-timeout "
            "bookmark stays on either way (rest.py's liveness check "
            "depends on it). Off = no under-traffic bookmarks, "
            "reconnects always relist — byte-identical on the wire"),
    Feature("BatchWriteTxn", False, ALPHA,
            "transactional batch write path (storage/mvcc.py txn + "
            "apiserver/registry.py): a {plural}:batchCreate / "
            "bindings:batch chunk commits as ONE MVCC transaction — "
            "one store lock hold, one contiguous revision range, one "
            "CRC-framed BATCH WAL record, one group-commit fsync, one "
            "replication log entry (wait_commit acks the chunk's "
            "final revision), one watch-delivery round — with "
            "validation+admission run as one batched pass per chunk "
            "(read-only admission lookups memoized chunk-wide) and "
            "the encode cache filled from the txn's echoed objects. "
            "Per-item rejections split-commit around the bad item; "
            "per-item status is preserved either way. Off = the "
            "per-object write loop, byte-identical wire AND WAL "
            "bytes"),
    Feature("ClusterMonitoring", True, BETA,
            "cluster-level TPU telemetry rollup (monitoring/"
            "aggregator.py): the controller-manager scrapes node "
            "/stats/summary into tpu_cluster_*/tpu_node_* series and "
            "a queryable snapshot (ktl top nodes|pods; the custom-"
            "metrics seam for autoscaling). Off = no scrape loop, no "
            "series"),
]}


class FeatureGates:
    def __init__(self, overrides: dict | None = None):
        self._enabled = {name: f.default for name, f in KNOWN_FEATURES.items()}
        for name, value in (overrides or {}).items():
            self.set(name, value)

    def enabled(self, name: str) -> bool:
        try:
            return self._enabled[name]
        except KeyError:
            raise ValueError(f"unknown feature gate {name!r} (known: "
                             f"{', '.join(sorted(KNOWN_FEATURES))})") from None

    def set(self, name: str, value: bool) -> None:
        if name not in KNOWN_FEATURES:
            raise ValueError(f"unknown feature gate {name!r} (known: "
                             f"{', '.join(sorted(KNOWN_FEATURES))})")
        if KNOWN_FEATURES[name].stage == GA and not value:
            raise ValueError(f"feature gate {name!r} is GA and cannot be "
                             f"disabled")
        self._enabled[name] = value

    def parse(self, spec: str) -> "FeatureGates":
        """Apply ``"Gate=true,Other=false"`` (the --feature-gates flag
        format). Returns self for chaining."""
        for part in filter(None, (p.strip() for p in spec.split(","))):
            name, eq, raw = part.partition("=")
            if not eq or raw.lower() not in ("true", "false"):
                raise ValueError(
                    f"feature gate must be <name>=true|false, got {part!r}")
            self.set(name.strip(), raw.lower() == "true")
        return self

    def as_dict(self) -> dict[str, bool]:
        return dict(self._enabled)

    def snapshot(self) -> dict[str, bool]:
        """Current gate values, for a later :meth:`restore` — the
        save/restore pair harnesses use to flip gates for one run
        without leaking them into the process."""
        return dict(self._enabled)

    def restore(self, snap: dict[str, bool]) -> None:
        """Reinstate a :meth:`snapshot` verbatim (bypasses the GA
        guard — a snapshot is by construction a legal state)."""
        self._enabled = dict(snap)


#: Process-global gates (reference: utilfeature.DefaultFeatureGate).
GATES = FeatureGates()
