"""lockdep — runtime lock-order and held-across-await checking.

The Linux-kernel-lockdep idea shrunk to this codebase: every
instrumented ``threading.Lock``/``RLock`` belongs to a named *class*
(e.g. ``"metrics.Metric"``), and the checker maintains a global graph
of observed acquisition order between classes. Two detectors:

- **Order inversion**: acquiring class B while holding class A records
  the edge A→B; a later acquisition of A while holding B is the
  classic AB/BA deadlock seed and raises :class:`LockOrderError`
  immediately (no need to actually hit the deadlock window).
- **Held across await**: a ``threading`` lock held while a coroutine
  yields to the event loop stalls every other coroutine that touches
  it (and inverts cooperative-scheduling assumptions). On acquire from
  a running loop the checker schedules a ``call_soon`` probe; the probe
  only runs once the coroutine yields, so "probe fired while the lock
  is still held" is exactly the violation. Recorded in
  :data:`VIOLATIONS` and logged (raising inside a loop callback would
  be swallowed by the loop's exception handler).

Gate: ``TPU_LOCKDEP=1`` (checked at :func:`make_lock` call time).
Disabled, :func:`make_lock` returns a plain stdlib lock — zero
overhead. Enable + construct explicitly with ``DepLock(name)`` in
tests.
"""
from __future__ import annotations

import asyncio
import logging
import os
import threading
from typing import Optional, Union

log = logging.getLogger("lockdep")

ENV_VAR = "TPU_LOCKDEP"

#: Held-across-await findings (order inversions raise instead): each
#: entry is a human-readable description. Tests assert on this.
VIOLATIONS: list[str] = []


def lockdep_enabled() -> bool:
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes", "on")


class LockOrderError(RuntimeError):
    """A→B lock order observed after B→A: deadlock-prone inversion."""


#: class name -> set of class names acquired while it was held (A -> B
#: meaning "A held when B acquired": A before B).
_edges: dict[str, set[str]] = {}
_edges_lock = threading.Lock()
_held = threading.local()  # per-thread stack of (class_name, DepLock)


def reset() -> None:
    """Drop the order graph and recorded violations (test isolation)."""
    with _edges_lock:
        _edges.clear()
    VIOLATIONS.clear()


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class DepLock:
    """Instrumented lock. API-compatible with threading.Lock/RLock for
    the subset this codebase uses (acquire/release/context manager)."""

    def __init__(self, name: str, rlock: bool = False):
        self.name = name
        self._inner: Union[threading.Lock, threading.RLock] = (
            threading.RLock() if rlock else threading.Lock())
        self._reentrant = rlock
        #: Hold id, bumped only on the 0->1 / 1->0 depth transitions —
        #: RLock re-entry keeps the id, so the await-probe can tell
        #: "still the same hold" from "released and re-acquired".
        self._gen = 0
        self._depth = 0  # RLock re-entry depth on the owning thread

    # -- checks -----------------------------------------------------------

    def _check_order(self) -> None:
        stack = _held_stack()
        for held_name, held_lock in stack:
            if held_name == self.name:
                continue  # same class (two metrics etc.): no ordering
            with _edges_lock:
                if self.name in _edges and held_name in _edges[self.name]:
                    raise LockOrderError(
                        f"lock order inversion: acquiring {self.name!r} "
                        f"while holding {held_name!r}, but the opposite "
                        f"order {self.name!r} -> {held_name!r} was "
                        f"observed earlier (AB/BA deadlock seed)")
                _edges.setdefault(held_name, set()).add(self.name)

    def _schedule_await_probe(self) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # not on an event-loop thread
        gen = self._gen
        def probe() -> None:
            if self._depth > 0 and self._gen == gen:
                msg = (f"lock {self.name!r} held across an await: the "
                       f"event loop ran while the lock was still held "
                       f"(acquired in a coroutine, not released before "
                       f"yielding)")
                VIOLATIONS.append(msg)
                log.error("lockdep: %s", msg)
        loop.call_soon(probe)

    # -- lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return False
        self._depth += 1
        if self._depth == 1:
            self._gen += 1
            self._check_order_safe()
            _held_stack().append((self.name, self))
            self._schedule_await_probe()
        return True

    def _check_order_safe(self) -> None:
        try:
            self._check_order()
        except LockOrderError:
            # Leave the lock in a consistent state before surfacing.
            self._depth -= 1
            self._gen += 1
            self._inner.release()
            raise

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._gen += 1
            stack = _held_stack()
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] is self:
                    del stack[i]
                    break
        self._inner.release()

    def locked(self) -> bool:
        return self._depth > 0

    def __enter__(self) -> "DepLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str, rlock: bool = False
              ) -> Union[threading.Lock, threading.RLock, DepLock]:
    """The factory components use: a plain stdlib lock normally, an
    instrumented :class:`DepLock` under ``TPU_LOCKDEP=1``."""
    if lockdep_enabled():
        return DepLock(name, rlock=rlock)
    return threading.RLock() if rlock else threading.Lock()
