"""Operation tracing — log slow multi-step operations with timings.

Reference: ``apiserver/pkg/util/trace/trace.go:33-79`` — create a Trace
at the top of an operation, mark steps as they complete, and
``LogIfLong`` emits one structured line (total + per-step durations)
ONLY when the operation exceeded its threshold. Used by the reference
scheduler (``generic_scheduler.go:110-141``) and apiserver handlers;
wired the same way here.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

log = logging.getLogger("trace")


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self, threshold: float,
                    logger: Optional[logging.Logger] = None) -> bool:
        """One line with per-step splits when total > threshold.
        Returns whether it logged (tests hook this)."""
        total = self.total_seconds()
        if total <= threshold:
            return False
        parts = []
        prev = self.start
        for ts, msg in self.steps:
            parts.append(f"{msg} {1e3 * (ts - prev):.1f}ms")
            prev = ts
        tail = 1e3 * (self.start + total - prev)
        if self.steps and tail > 0.05:
            parts.append(f"(rest) {tail:.1f}ms")
        ctx = " ".join(f"{k}={v}" for k, v in self.fields.items())
        (logger or log).info("Trace %r%s (%.1fms): %s", self.name,
                             f" [{ctx}]" if ctx else "", 1e3 * total,
                             "; ".join(parts) or "no steps")
        return True

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, *exc) -> None:
        # Context-manager use defaults to a 100ms threshold.
        self.log_if_long(0.1)
