"""Operation tracing — log slow multi-step operations with timings.

Reference: ``apiserver/pkg/util/trace/trace.go:33-79`` — create a Trace
at the top of an operation, mark steps as they complete, and
``LogIfLong`` emits one structured line (total + per-step durations)
ONLY when the operation exceeded its threshold. Used by the reference
scheduler (``generic_scheduler.go:110-141``) and apiserver handlers;
wired the same way here.

Folded into the span layer (tracing/): when tracing is armed and a
sampled trace context is current, the Trace ALSO records a span whose
events are the steps — so ``ktl trace pod`` shows the op's internal
splits inline. Disarmed, behavior (and every log line) is
byte-identical to the pre-span Trace: the span half is the shared
no-op singleton.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from .. import tracing

log = logging.getLogger("trace")

#: The reference's LogIfLong threshold (context-manager default).
DEFAULT_THRESHOLD = 0.1


class Trace:
    def __init__(self, name: str, threshold: float = DEFAULT_THRESHOLD,
                 **fields):
        """``threshold``: seconds the context-manager form (and
        argument-less :meth:`log_if_long`) logs above — the previously
        hard-coded 100ms, now a parameter per call site."""
        self.name = name
        self.threshold = threshold
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[tuple[float, str]] = []
        #: Span sibling (NOOP unless armed + sampled context current).
        self._span = tracing.start_span(name, component="optrace",
                                        attrs=fields or None)

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))
        self._span.event(msg)

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self, threshold: Optional[float] = None,
                    logger: Optional[logging.Logger] = None) -> bool:
        """One line with per-step splits when total > threshold
        (default: this Trace's own threshold). Returns whether it
        logged (tests hook this). Also ends the span half (idempotent
        — terminal branches may each call this)."""
        self._span.end()
        if threshold is None:
            threshold = self.threshold
        total = self.total_seconds()
        if total <= threshold:
            return False
        parts = []
        prev = self.start
        for ts, msg in self.steps:
            parts.append(f"{msg} {1e3 * (ts - prev):.1f}ms")
            prev = ts
        tail = 1e3 * (self.start + total - prev)
        if self.steps and tail > 0.05:
            parts.append(f"(rest) {tail:.1f}ms")
        ctx = " ".join(f"{k}={v}" for k, v in self.fields.items())
        (logger or log).info("Trace %r%s (%.1fms): %s", self.name,
                             f" [{ctx}]" if ctx else "", 1e3 * total,
                             "; ".join(parts) or "no steps")
        return True

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, *exc) -> None:
        # Context-manager use logs at this Trace's threshold (the old
        # hard-coded 100ms is the constructor default).
        self.log_if_long()
