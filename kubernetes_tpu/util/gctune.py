"""CPython GC policy for control-plane processes.

The reference's control plane is Go, whose GC is concurrent; CPython's
generational collector is stop-the-world, and its gen2 pass SCANS every
tracked object. At 30k-pod density that is millions of live dataclass
nodes: measured in the density harness, 35 automatic gen2 collections
cost 20.9s of pauses (max 1314ms) in a 120s run — the entire
bind-latency p99 tail and ~18% of wall clock.

The framework's API objects are TREES (no parent backrefs), so they die
by reference counting; gen2 finds almost nothing to free (RSS measured
flat at ~308MB across a 30k run with gen2 effectively off). True cycles
(exception tracebacks, closures) accumulate slowly, so gen2 is not
disabled — its threshold is raised so it runs orders of magnitude less
often, bounding leak growth without putting 1.3s pauses on the hot
path.

Called by long-running control-plane entrypoints (scheduler start,
apiserver main, cluster composer). Idempotent and process-global by
nature (CPython has one collector).
"""
from __future__ import annotations

import gc

#: gen0/gen1 are left exactly as the embedder configured them (cheap,
#: young garbage is real); ONLY gen2 is raised — it fires after 10_000
#: gen1 passes instead of 10, rare enough to stay off
#: latency-sensitive windows, finite so cycle leaks stay bounded in
#: week-long processes.
_GEN2_THRESHOLD = 10_000


def tune_control_plane_gc() -> None:
    gen0, gen1, gen2 = gc.get_threshold()
    if gen2 < _GEN2_THRESHOLD:
        gc.set_threshold(gen0, gen1, _GEN2_THRESHOLD)
