"""Event-loop lag probe — the shared loop-health instrument.

One coroutine per probed loop: how late a short sleep fires is the
time the loop spent busy (or starved by sibling processes) per tick.
``_sum``/``_count`` deltas let bench harnesses attribute per-phase
wall-vs-loop time; the busy gauge is a local EWMA for eyeballing
/metrics. First grown for the apiserver router/shard loops (PR 9);
the scheduler loop joined the family here — one implementation, so
the probes cannot drift.
"""
from __future__ import annotations

import asyncio

#: Probe cadence; cheap by construction (one timer per loop).
PROBE_INTERVAL = 0.05


async def loop_lag_probe(lag_hist, busy_gauge,
                         interval: float = PROBE_INTERVAL,
                         **labels) -> None:
    """Run forever (callers own the task): observe per-tick lag in ms
    into ``lag_hist`` and an EWMA busy fraction into ``busy_gauge``,
    both under ``labels``."""
    loop = asyncio.get_running_loop()
    busy = 0.0
    while True:
        t0 = loop.time()
        await asyncio.sleep(interval)
        lag = max(0.0, loop.time() - t0 - interval)
        lag_hist.observe(lag * 1e3, **labels)
        busy = 0.8 * busy + 0.2 * (lag / (lag + interval))
        busy_gauge.set(round(busy, 4), **labels)
