"""Compact framed wire codec — the LIST/watch twin of the scheduler
fast path (gate ``CompactWireCodec``, alpha, default off).

Reference motivation: the apiserver negotiates protobuf on the hot
path because wire-codec CPU dominates the control plane at density
scale (``apimachinery/pkg/runtime/serializer/protobuf``); this repo's
go/no-go instrument (``perf/decode_share.py``) puts the JSON share at
~7% and RISING with every fan-out win. The codec here is deliberately
small: **length-prefixed msgpack frames**, negotiated per request via
``Accept``/``Content-Type``. JSON remains the default and the
fallback — a client that never asks, a server with the gate off, or a
host without the msgpack wheel all keep the existing byte-identical
JSON surface.

Wire format (``application/x-ktpu-compact``):

- **frame** — 4-byte big-endian payload length + msgpack payload.
- **LIST body** — frame 0 is the envelope map ``{"kind": "List",
  "api_version": "core/v1", "metadata": {"resource_version": str},
  "n": N}``; frames 1..N are the items. Per-item bytes are cached in
  the apiserver's serialize-once encode cache beside the JSON lines
  (same ``(key, revision)`` identity, ``which`` suffixed ``#c``), so
  fan-out reuse holds for both codecs.
- **watch stream** — one frame per event: the map ``{"type": etype,
  "object": obj}``, hand-assembled as a fixmap header + pre-encoded
  object bytes so the cached per-revision encoding is reused without
  a re-pack (:func:`event_frame`). Bookmarks are ordinary events.
- **write bodies** (the full write path: ``CREATE`` /
  ``{plural}:batchCreate`` / ``bindings:batch`` requests, negotiated
  per request via ``Content-Type``; and their responses, via
  ``Accept``) — a single-object body is ONE frame holding the object
  map; a multi-item body is an envelope frame carrying ``"n": N``
  (plus any response fields, e.g. ``"kind": "BatchResult"``) followed
  by N item frames. :func:`decode_body` tells the two apart by the
  reserved top-level ``"n"`` key — no wire kind carries one — and
  yields exactly the dict shape the JSON path's ``json.loads`` would
  (items folded back under ``"items"``), so every existing caller
  decodes identically.
- **body templates** — :class:`BodyTemplate` pre-encodes a write body
  whose items differ only in one string field (a load generator's pod
  name): render is a small ``packb`` of the varying value between two
  cached byte halves, so bulk submitters pay ZERO per-item object
  encode (ROADMAP 3b: the harness's own encode cost was capping the
  measurement).

Value model: msgpack round-trips exactly the JSON value universe the
scheme's ``to_dict`` emits (str/float/int/bool/None/list/str-keyed
dict) — the golden corpus test pins compact decode output equal to
the JSON path's for every core kind, unicode and large lists
included.
"""
from __future__ import annotations

import json as _json
import struct
from typing import Callable, Iterator, Optional

try:  # the wheel is baked into the image; gate stays inert without it
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - exercised only on bare hosts
    _msgpack = None

from ..metrics.registry import Counter

#: Negotiated media type (client Accept -> server Content-Type).
CONTENT_TYPE = "application/x-ktpu-compact"

_LEN = struct.Struct(">I")

CODEC_WIRE_REQUESTS = Counter(
    "codec_wire_requests_total",
    "Wire requests/streams served or consumed per negotiated codec",
    labels=("codec", "op"))

CODEC_WIRE_BYTES = Counter(
    "codec_wire_bytes_total",
    "Payload bytes produced per negotiated codec and operation",
    labels=("codec", "op"))


def available() -> bool:
    """True when the msgpack wheel is importable on this host."""
    return _msgpack is not None


def enabled() -> bool:
    """Gate + wheel: the compact codec may be offered/requested."""
    if _msgpack is None:
        return False
    from .features import GATES
    return GATES.enabled("CompactWireCodec")


def accepts_compact(accept_header: str) -> bool:
    """Does an ``Accept`` header ask for the compact media type?"""
    return CONTENT_TYPE in (accept_header or "")


def accept_header() -> Optional[dict]:
    """The client-side offer: ONE place builds the negotiation string
    every client (RESTClient, loadgen's raw watcher) sends, so they
    can never drift apart. None when the gate/wheel says JSON-only —
    callers then send byte-identical ungated requests."""
    if not enabled():
        return None
    return {"Accept": CONTENT_TYPE + ", application/json"}


def write_headers() -> Optional[dict]:
    """The write-path negotiation twin of :func:`accept_header`:
    ``Content-Type`` names the compact request body, ``Accept`` offers
    compact for the response (a JSON answer stays acceptable — a
    server with its gate off decodes nothing and 415s, never guesses).
    None when the gate/wheel says JSON-only."""
    if not enabled():
        return None
    return {"Content-Type": CONTENT_TYPE,
            "Accept": CONTENT_TYPE + ", application/json"}


def cache_which(which: str, codec: str) -> str:
    """Encode-cache ``which`` for a codec: compact payloads live
    beside the JSON lines under a ``#c`` suffix — same ``(key,
    revision)`` identity, same write invalidation. One mapping shared
    by every cache reader/writer (registry LIST/GET/watch, the
    codec-pool completion path) so lookups and inserts can never use
    different keys."""
    return which if codec == "json" else which + "#c"


def encode_wire(value, codec: str) -> bytes:
    """One value -> wire bytes under ``codec`` — the single encode
    dispatch the inline LIST/watch paths share (the pool offload uses
    the module-level worker twins)."""
    if codec == "json":
        import json
        return json.dumps(value, separators=(",", ":")).encode()
    return encode_obj(value)


# -- scalar object codec ----------------------------------------------------

def encode_obj(value) -> bytes:
    """msgpack bytes for one JSON-model value (the compact analog of
    ``json.dumps(value, separators=(",", ":")).encode()``)."""
    return _msgpack.packb(value, use_bin_type=True)


def decode_obj(raw: bytes):
    """Inverse of :func:`encode_obj`; str keys/values come back as str
    (never bytes), matching ``json.loads`` output exactly."""
    return _msgpack.unpackb(raw, raw=False, strict_map_key=False)


# -- framing ----------------------------------------------------------------

def frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental frame parser for streamed bodies (watch). Feed raw
    socket chunks in any fragmentation; complete payloads come out in
    order. Bounded by one frame of buffered bytes plus the unconsumed
    tail of the last chunk."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> Iterator[bytes]:
        self._buf.extend(chunk)
        while True:
            if len(self._buf) < _LEN.size:
                return
            (n,) = _LEN.unpack_from(self._buf, 0)
            end = _LEN.size + n
            if len(self._buf) < end:
                return
            payload = bytes(self._buf[_LEN.size:end])
            del self._buf[:end]
            yield payload

    @property
    def pending(self) -> int:
        """Buffered bytes not yet forming a complete frame — nonzero
        after a finite body means truncation."""
        return len(self._buf)


# -- framed bodies (LIST/batch responses, write-path requests) --------------

def list_envelope(revision: int, n_items: int,
                  continue_token: str = "") -> bytes:
    meta = {"resource_version": str(revision)}
    if continue_token:
        meta["continue"] = continue_token
    return encode_obj({"kind": "List", "api_version": "core/v1",
                       "metadata": meta, "n": n_items})


def encode_list_body(revision: int, item_payloads: list[bytes],
                     continue_token: str = "") -> bytes:
    """Assemble a compact LIST response from per-item msgpack payloads
    (already encoded — typically straight out of the encode cache)."""
    parts = [frame(list_envelope(revision, len(item_payloads),
                                 continue_token))]
    parts.extend(_LEN.pack(len(p)) + p for p in item_payloads)
    return b"".join(parts)


def encode_obj_body(value) -> bytes:
    """One-object body (single CREATE request/response, one binding):
    exactly one frame holding the object map."""
    return frame(encode_obj(value))


def encode_batch_body(item_payloads: list[bytes],
                      envelope: Optional[dict] = None) -> bytes:
    """Multi-item body from per-item msgpack payloads (pre-encoded:
    template renders, cache lines, or plain ``encode_obj`` output):
    the envelope frame gains ``"n"`` and frames 1..N are the items.
    Inverse of :func:`decode_body`'s enveloped branch."""
    env = dict(envelope or {})
    env["n"] = len(item_payloads)
    parts = [frame(encode_obj(env))]
    parts.extend(_LEN.pack(len(p)) + p for p in item_payloads)
    return b"".join(parts)


def decode_body(body: bytes):
    """Any compact body back to the exact value shape the JSON path's
    ``json.loads`` yields. An envelope frame (a map carrying the
    reserved ``"n"`` key — no wire kind has one) folds its item frames
    back under ``"items"``; anything else must be a single frame and
    decodes as-is. Truncated or trailing bytes are a ValueError, never
    a silently short result."""
    dec = FrameDecoder()
    frames = [decode_obj(p) for p in dec.feed(body)]
    if dec.pending:
        raise ValueError(f"compact body truncated: {dec.pending} "
                         f"trailing bytes do not form a frame")
    if not frames:
        raise ValueError("compact body has no frames")
    head = frames[0]
    if isinstance(head, dict) and "n" in head:
        env = dict(head)
        n = env.pop("n")
        items = frames[1:]
        if len(items) != n:
            raise ValueError(f"compact body truncated: envelope says "
                             f"{n} items, got {len(items)}")
        env["items"] = items
        return env
    if len(frames) != 1:
        raise ValueError(f"compact body has {len(frames)} frames but "
                         f"no envelope")
    return head


def decode_list_body(body: bytes) -> dict:
    """Client half of the LIST fast path — the enveloped branch of
    :func:`decode_body` (kept as a named entry point for the readers
    that only ever see LIST bodies: the loadgen's raw watcher)."""
    return decode_body(body)


# -- watch events -----------------------------------------------------------

def _packed_key(name: str) -> bytes:
    return _msgpack.packb(name) if _msgpack is not None else b""


_KEY_TYPE = _packed_key("type")
_KEY_OBJECT = _packed_key("object")
_KEY_STATUS = _packed_key("status")


def event_frame(etype: str, obj_payload: bytes) -> bytes:
    """One watch event as a frame, reusing the object's cached msgpack
    bytes: a hand-built 2-entry fixmap header + the two pairs — valid
    msgpack, zero re-encode of the (large) object payload."""
    payload = (b"\x82" + _KEY_TYPE + _msgpack.packb(etype)
               + _KEY_OBJECT + obj_payload)
    return _LEN.pack(len(payload)) + payload


def decode_event(payload: bytes) -> dict:
    """{"type": ..., "object": ...} from one watch frame payload."""
    return decode_obj(payload)


# -- batch-result items -----------------------------------------------------

def batch_item_payload(status: int, obj_payload: Optional[bytes] = None,
                       error: Optional[dict] = None) -> bytes:
    """One BatchResult item as an (unframed) msgpack payload. A
    success carrying an object embeds the SERIALIZE-ONCE cached bytes
    verbatim — fixmap header + pre-encoded payload, the
    :func:`event_frame` trick — so a 512-item echo response costs zero
    per-object re-packs."""
    if error is not None:
        return encode_obj({"status": status, "error": error})
    if obj_payload is None:
        return encode_obj({"status": status})
    return (b"\x82" + _KEY_STATUS + _msgpack.packb(status)
            + _KEY_OBJECT + obj_payload)


# -- pre-encoded body templates ---------------------------------------------

_TEMPLATE_SENTINEL = "\x00ktpu/body-template\x00"


class BodyTemplate:
    """Pre-encoded msgpack payload for one JSON-model dict in which a
    SINGLE string field varies (``vary`` is its key path, e.g.
    ``("metadata", "name")``). The dict is encoded once with a
    sentinel at the varying slot and split around it;
    :meth:`render` is then two byte concats + one small ``packb`` —
    no per-item ``to_dict`` walk, no per-item object encode. The bulk
    submitter's whole batch body becomes
    ``encode_batch_body([tmpl.render(name) for name in names])``."""

    def __init__(self, value: dict, vary: tuple):
        if not vary:
            raise ValueError("vary path must name at least one key")
        top = dict(value)
        cur = top
        for k in vary[:-1]:
            cur[k] = dict(cur[k])  # copy only the spine being edited
            cur = cur[k]
        cur[vary[-1]] = _TEMPLATE_SENTINEL
        blob = encode_obj(top)
        sep = _msgpack.packb(_TEMPLATE_SENTINEL)
        pre, found, suf = blob.partition(sep)
        if not found or sep in suf:
            raise ValueError("template payload must contain the vary "
                             "slot exactly once")
        self._pre, self._suf = pre, suf

    def render(self, value: str) -> bytes:
        """The item payload with ``value`` at the varying slot —
        byte-identical to ``encode_obj`` of the substituted dict."""
        return self._pre + _msgpack.packb(value) + self._suf


# -- per-verb codec seams (decode_share attribution) ------------------------
# Thin module-level wrappers dispatched by verb × direction so cProfile
# cumtime attributes wire-codec CPU to the create/batch/bind paths by
# FRAME NAME (perf/decode_share.py reads these); behavior is exactly
# the shared json/msgpack codepaths, both codecs.

def _decode_any(raw: bytes, codec: str):
    if codec == "compact":
        return decode_body(raw)
    return _json.loads(raw)


def decode_request_create(raw: bytes, codec: str = "json"):
    return _decode_any(raw, codec)


def decode_request_batch_create(raw: bytes, codec: str = "json"):
    return _decode_any(raw, codec)


def decode_request_bind(raw: bytes, codec: str = "json"):
    return _decode_any(raw, codec)


def decode_request_other(raw: bytes, codec: str = "json"):
    return _decode_any(raw, codec)


_DECODE_SEAMS = {"create": decode_request_create,
                 "batch_create": decode_request_batch_create,
                 "bind": decode_request_bind}


def decode_request(raw: bytes, codec: str, op: str = "other"):
    """Request-body decode through the ``op``-named seam (the
    apiserver's ``_body_obj`` inline path; the codec pool's offload
    decodes in worker processes outside any profile)."""
    return _DECODE_SEAMS.get(op, decode_request_other)(raw, codec)


def dumps_response_batch_create(doc) -> str:
    """JSON BatchResult encode seam for ``{plural}:batchCreate`` —
    byte-identical to ``web.json_response``'s default ``json.dumps``."""
    return _json.dumps(doc)


def dumps_response_bind(doc) -> str:
    """JSON BatchResult encode seam for ``bindings:batch``."""
    return _json.dumps(doc)


def encode_response_create(assemble: Callable[[], bytes]) -> bytes:
    """Create-response assembly seam (cached-payload fetch + framing)."""
    return assemble()


def encode_response_batch_create(assemble: Callable[[], bytes]) -> bytes:
    """Compact BatchResult assembly seam for ``:batchCreate``."""
    return assemble()


def encode_response_bind(assemble: Callable[[], bytes]) -> bytes:
    """Compact BatchResult assembly seam for ``bindings:batch``."""
    return assemble()


# -- worker-process encode (codec pool) -------------------------------------

def encode_many(values: list) -> list[bytes]:
    """Compact analog of the codec pool's ``_encode_many``; module
    level so it pickles by reference into pool workers."""
    packb = _msgpack.packb
    return [packb(v, use_bin_type=True) for v in values]


def count_request(codec: str, op: str, nbytes: Optional[int] = None) -> None:
    """One metrics seam for both codecs so the codec_wire_* families
    compare like for like (the JSON fast path counts here too)."""
    CODEC_WIRE_REQUESTS.inc(codec=codec, op=op)
    if nbytes:
        CODEC_WIRE_BYTES.inc(nbytes, codec=codec, op=op)
