"""Compact framed wire codec — the LIST/watch twin of the scheduler
fast path (gate ``CompactWireCodec``, alpha, default off).

Reference motivation: the apiserver negotiates protobuf on the hot
path because wire-codec CPU dominates the control plane at density
scale (``apimachinery/pkg/runtime/serializer/protobuf``); this repo's
go/no-go instrument (``perf/decode_share.py``) puts the JSON share at
~7% and RISING with every fan-out win. The codec here is deliberately
small: **length-prefixed msgpack frames**, negotiated per request via
``Accept``/``Content-Type``. JSON remains the default and the
fallback — a client that never asks, a server with the gate off, or a
host without the msgpack wheel all keep the existing byte-identical
JSON surface.

Wire format (``application/x-ktpu-compact``):

- **frame** — 4-byte big-endian payload length + msgpack payload.
- **LIST body** — frame 0 is the envelope map ``{"kind": "List",
  "api_version": "core/v1", "metadata": {"resource_version": str},
  "n": N}``; frames 1..N are the items. Per-item bytes are cached in
  the apiserver's serialize-once encode cache beside the JSON lines
  (same ``(key, revision)`` identity, ``which`` suffixed ``#c``), so
  fan-out reuse holds for both codecs.
- **watch stream** — one frame per event: the map ``{"type": etype,
  "object": obj}``, hand-assembled as a fixmap header + pre-encoded
  object bytes so the cached per-revision encoding is reused without
  a re-pack (:func:`event_frame`). Bookmarks are ordinary events.

Value model: msgpack round-trips exactly the JSON value universe the
scheme's ``to_dict`` emits (str/float/int/bool/None/list/str-keyed
dict) — the golden corpus test pins compact decode output equal to
the JSON path's for every core kind, unicode and large lists
included.
"""
from __future__ import annotations

import struct
from typing import Iterator, Optional

try:  # the wheel is baked into the image; gate stays inert without it
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - exercised only on bare hosts
    _msgpack = None

from ..metrics.registry import Counter

#: Negotiated media type (client Accept -> server Content-Type).
CONTENT_TYPE = "application/x-ktpu-compact"

_LEN = struct.Struct(">I")

CODEC_WIRE_REQUESTS = Counter(
    "codec_wire_requests_total",
    "Wire requests/streams served or consumed per negotiated codec",
    labels=("codec", "op"))

CODEC_WIRE_BYTES = Counter(
    "codec_wire_bytes_total",
    "Payload bytes produced per negotiated codec and operation",
    labels=("codec", "op"))


def available() -> bool:
    """True when the msgpack wheel is importable on this host."""
    return _msgpack is not None


def enabled() -> bool:
    """Gate + wheel: the compact codec may be offered/requested."""
    if _msgpack is None:
        return False
    from .features import GATES
    return GATES.enabled("CompactWireCodec")


def accepts_compact(accept_header: str) -> bool:
    """Does an ``Accept`` header ask for the compact media type?"""
    return CONTENT_TYPE in (accept_header or "")


def accept_header() -> Optional[dict]:
    """The client-side offer: ONE place builds the negotiation string
    every client (RESTClient, loadgen's raw watcher) sends, so they
    can never drift apart. None when the gate/wheel says JSON-only —
    callers then send byte-identical ungated requests."""
    if not enabled():
        return None
    return {"Accept": CONTENT_TYPE + ", application/json"}


def cache_which(which: str, codec: str) -> str:
    """Encode-cache ``which`` for a codec: compact payloads live
    beside the JSON lines under a ``#c`` suffix — same ``(key,
    revision)`` identity, same write invalidation. One mapping shared
    by every cache reader/writer (registry LIST/GET/watch, the
    codec-pool completion path) so lookups and inserts can never use
    different keys."""
    return which if codec == "json" else which + "#c"


def encode_wire(value, codec: str) -> bytes:
    """One value -> wire bytes under ``codec`` — the single encode
    dispatch the inline LIST/watch paths share (the pool offload uses
    the module-level worker twins)."""
    if codec == "json":
        import json
        return json.dumps(value, separators=(",", ":")).encode()
    return encode_obj(value)


# -- scalar object codec ----------------------------------------------------

def encode_obj(value) -> bytes:
    """msgpack bytes for one JSON-model value (the compact analog of
    ``json.dumps(value, separators=(",", ":")).encode()``)."""
    return _msgpack.packb(value, use_bin_type=True)


def decode_obj(raw: bytes):
    """Inverse of :func:`encode_obj`; str keys/values come back as str
    (never bytes), matching ``json.loads`` output exactly."""
    return _msgpack.unpackb(raw, raw=False, strict_map_key=False)


# -- framing ----------------------------------------------------------------

def frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental frame parser for streamed bodies (watch). Feed raw
    socket chunks in any fragmentation; complete payloads come out in
    order. Bounded by one frame of buffered bytes plus the unconsumed
    tail of the last chunk."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> Iterator[bytes]:
        self._buf.extend(chunk)
        while True:
            if len(self._buf) < _LEN.size:
                return
            (n,) = _LEN.unpack_from(self._buf, 0)
            end = _LEN.size + n
            if len(self._buf) < end:
                return
            payload = bytes(self._buf[_LEN.size:end])
            del self._buf[:end]
            yield payload


# -- LIST bodies ------------------------------------------------------------

def list_envelope(revision: int, n_items: int,
                  continue_token: str = "") -> bytes:
    meta = {"resource_version": str(revision)}
    if continue_token:
        meta["continue"] = continue_token
    return encode_obj({"kind": "List", "api_version": "core/v1",
                       "metadata": meta, "n": n_items})


def encode_list_body(revision: int, item_payloads: list[bytes],
                     continue_token: str = "") -> bytes:
    """Assemble a compact LIST response from per-item msgpack payloads
    (already encoded — typically straight out of the encode cache)."""
    parts = [frame(list_envelope(revision, len(item_payloads),
                                 continue_token))]
    parts.extend(_LEN.pack(len(p)) + p for p in item_payloads)
    return b"".join(parts)


def decode_list_body(body: bytes) -> dict:
    """Client half: a compact LIST body back to the dict shape the JSON
    path's ``resp.json()`` yields ({"kind", "api_version", "metadata",
    "items": [...]}), so every existing caller decodes identically."""
    dec = FrameDecoder()
    frames = iter(dec.feed(body))
    try:
        env = decode_obj(next(frames))
    except StopIteration:
        raise ValueError("compact LIST body has no envelope frame") \
            from None
    n = env.pop("n", 0)
    items = [decode_obj(p) for p in frames]
    if len(items) != n:
        raise ValueError(f"compact LIST body truncated: envelope says "
                         f"{n} items, got {len(items)}")
    env["items"] = items
    return env


# -- watch events -----------------------------------------------------------

def _packed_key(name: str) -> bytes:
    return _msgpack.packb(name) if _msgpack is not None else b""


_KEY_TYPE = _packed_key("type")
_KEY_OBJECT = _packed_key("object")


def event_frame(etype: str, obj_payload: bytes) -> bytes:
    """One watch event as a frame, reusing the object's cached msgpack
    bytes: a hand-built 2-entry fixmap header + the two pairs — valid
    msgpack, zero re-encode of the (large) object payload."""
    payload = (b"\x82" + _KEY_TYPE + _msgpack.packb(etype)
               + _KEY_OBJECT + obj_payload)
    return _LEN.pack(len(payload)) + payload


def decode_event(payload: bytes) -> dict:
    """{"type": ..., "object": ...} from one watch frame payload."""
    return decode_obj(payload)


# -- worker-process encode (codec pool) -------------------------------------

def encode_many(values: list) -> list[bytes]:
    """Compact analog of the codec pool's ``_encode_many``; module
    level so it pickles by reference into pool workers."""
    packb = _msgpack.packb
    return [packb(v, use_bin_type=True) for v in values]


def count_request(codec: str, op: str, nbytes: Optional[int] = None) -> None:
    """One metrics seam for both codecs so the codec_wire_* families
    compare like for like (the JSON fast path counts here too)."""
    CODEC_WIRE_REQUESTS.inc(codec=codec, op=op)
    if nbytes:
        CODEC_WIRE_BYTES.inc(nbytes, codec=codec, op=op)
