"""HPA-analog autoscaling decisions for inference serving.

Pure functions + a small per-service state record, in the
``queueing/fairshare.py`` style: the controller feeds one
:class:`MetricsSample` per tick (derived from ``ClusterMonitor.
latest()``) and gets a :class:`Decision` back — no API objects, no
I/O, so the scale-up → stabilize → scale-down choreography is
unit-testable over a synthetic feed.

The control law (reference: ``replica_calculator.go`` shape, adapted
to serving):

    desired = ceil(reporting * utilization / target_utilization)
              [+ ready-but-not-reporting replicas when scaling down]

where ``utilization`` is the mean busy fraction the model servers
report (the fraction of wall time spent decoding — saturating at 1.0,
which is why the target defaults to 0.65: headroom IS the scale-up
signal), and ready replicas missing from the snapshot fold in
conservatively (idle on the way up, at-target on the way down — see
:func:`recommend`). Guards, in order:

- **staleness**: a snapshot older than ``max_snapshot_age`` REFUSES to
  act (the satellite contract for ``ClusterMonitor.latest()``'s
  ``age_seconds`` field — frozen numbers must not drive scaling);
- **tolerance** (±0.1 around target): no thrash inside the band;
- **rate limits**: at most ``scale_up_max_step`` replicas added /
  ``scale_down_max_step`` removed per decision;
- **scale-down stabilization**: shrink only to the HIGHEST
  recommendation seen inside the window (the reference's
  downscale-stabilization), so a burst's trough does not collapse the
  fleet the moment traffic dips;
- clamp to ``[min_replicas, max_replicas]``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ..metrics.registry import Counter, Gauge

#: Dead band around the utilization target (reference:
#: --horizontal-pod-autoscaler-tolerance).
TOLERANCE = 0.1

#: Defaults for spec fields left 0 (the admission defaulter fills them
#: for gated creates; these cover direct engine use and synthetics).
DEFAULT_SCALE_UP_STEP = 4
DEFAULT_SCALE_DOWN_STEP = 1

DESIRED = Gauge(
    "inference_autoscaler_desired_replicas",
    "Autoscaler's current replica target per InferenceService",
    labels=("service",))

UTILIZATION = Gauge(
    "inference_autoscaler_utilization",
    "Mean busy fraction observed across a service's replicas (0..1)",
    labels=("service",))

SNAPSHOT_AGE = Gauge(
    "inference_autoscaler_snapshot_age_seconds",
    "Age of the ClusterMonitor snapshot behind the last decision",
    labels=("service",))

SCALE_EVENTS = Counter(
    "inference_autoscaler_scale_events_total",
    "Replica-target changes by direction",
    labels=("service", "direction"))

STALE_REFUSALS = Counter(
    "inference_autoscaler_stale_refusals_total",
    "Decisions refused because the metrics snapshot was stale",
    labels=("service",))


@dataclass
class MetricsSample:
    """One tick's observation of a service, derived from the monitor
    snapshot by the controller (or synthesized by tests)."""
    #: Mean busy fraction across replicas that reported (0..1).
    utilization: float = 0.0
    #: Aggregate decode throughput across replicas (tokens/s).
    tokens_per_sec: float = 0.0
    #: Replicas that actually reported metrics this tick.
    reporting: int = 0
    #: Snapshot age (ClusterMonitor.latest()["age_seconds"]).
    age_seconds: float = 0.0


@dataclass
class Decision:
    desired: int
    reason: str
    #: True when the engine refused to act (stale feed / no data):
    #: ``desired`` then just echoes the current target.
    refused: bool = False


@dataclass
class ServiceState:
    """Per-service memory between ticks (controller-held; rebuilt from
    scratch on controller restart — the stabilization window then
    restarts too, which only ever delays a scale-down)."""
    #: (monotonic time, recommendation) pairs inside the window.
    recommendations: list[tuple[float, int]] = field(default_factory=list)
    last_desired: int = 0


def recommend(current: int, ready: int, sample: MetricsSample,
              target_utilization: float) -> tuple[int, str]:
    """The raw control law, before guards: what replica count would put
    mean utilization at target? Ready replicas MISSING from the metrics
    snapshot (scrape lag after a scale-up) fold in conservatively, the
    reference replica_calculator move: assumed idle when scaling up (so
    they cannot amplify the answer) and assumed at-target when scaling
    down (so a fleet whose load is simply unknown never shrinks on one
    idle reporter's word)."""
    target = min(max(target_utilization, 0.05), 1.0)
    if ready <= 0 or sample.reporting <= 0:
        return current, "no replicas reporting"
    util = max(sample.utilization, 0.0)
    ratio = util / target
    missing = max(ready - sample.reporting, 0)
    if abs(ratio - 1.0) <= TOLERANCE:
        return current, f"within tolerance (util {util:.2f})"
    if ratio > 1.0:
        # Missing replicas at 0 load: ceil(reporting * ratio) IS that
        # fold. Capacity already launching (current > ready) counts —
        # do not re-order what is already on the way.
        raw = max(math.ceil(sample.reporting * ratio), current)
    else:
        # Missing replicas at target: each holds its own seat.
        raw = math.ceil(sample.reporting * ratio) + missing
    return raw, f"util {util:.2f} vs target {target:.2f}"


def decide(spec, current: int, ready: int, sample: Optional[MetricsSample],
           state: ServiceState, now: float,
           max_snapshot_age: float = 30.0) -> Decision:
    """One autoscaler tick. ``spec`` is an InferenceServiceSpec (or any
    object with its scaling fields); ``current`` the present replica
    target; ``ready`` the replicas actually serving; ``now`` a
    monotonic clock (injected — the engine never reads time itself).
    """
    lo = max(spec.min_replicas, 0) or 1
    hi = max(spec.max_replicas, lo)
    if sample is None or sample.age_seconds > max_snapshot_age:
        # Refusal, not a decision: frozen numbers must not scale the
        # fleet (and must not age out the stabilization window either,
        # so no recommendation is recorded).
        age = sample.age_seconds if sample is not None else float("inf")
        return Decision(desired=min(max(current, lo), hi), refused=True,
                        reason=f"metrics snapshot stale "
                               f"({age:.1f}s > {max_snapshot_age:.0f}s)")
    raw, why = recommend(current, ready, sample, spec.target_utilization)
    raw = min(max(raw, lo), hi)

    # Scale-down stabilization: remember this recommendation, then only
    # shrink to the window's MAXIMUM.
    window = max(spec.scale_down_stabilization_seconds, 0.0)
    state.recommendations.append((now, raw))
    state.recommendations = [(t, r) for t, r in state.recommendations
                             if now - t <= window]
    floor = max((r for _t, r in state.recommendations), default=raw)

    desired = raw
    if desired < current:
        desired = min(current, floor)
        if desired > raw:
            why += f"; held by stabilization window ({window:.0f}s)"

    up_step = spec.scale_up_max_step or DEFAULT_SCALE_UP_STEP
    down_step = spec.scale_down_max_step or DEFAULT_SCALE_DOWN_STEP
    if desired > current + up_step:
        desired = current + up_step
        why += f"; rate-limited to +{up_step}"
    elif desired < current - down_step:
        desired = current - down_step
        why += f"; rate-limited to -{down_step}"
    desired = min(max(desired, lo), hi)
    return Decision(desired=desired, reason=why)


def export_metrics(service: str, decision: Decision,
                   sample: Optional[MetricsSample], current: int) -> None:
    """Publish the ``inference_autoscaler_*`` family for one tick."""
    DESIRED.set(float(decision.desired), service=service)
    if sample is not None:
        UTILIZATION.set(round(sample.utilization, 4), service=service)
        if math.isfinite(sample.age_seconds):
            SNAPSHOT_AGE.set(round(sample.age_seconds, 3), service=service)
    if decision.refused:
        STALE_REFUSALS.inc(service=service)
    elif decision.desired > current:
        SCALE_EVENTS.inc(service=service, direction="up")
    elif decision.desired < current:
        SCALE_EVENTS.inc(service=service, direction="down")
