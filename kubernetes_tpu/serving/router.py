"""Slice-topology-aware endpoint routing for inference traffic.

The client-side load balancer the serving loadgen (and any gateway
embedding this framework) balances requests with — the consumer of the
same Endpoints informer the per-node proxy programs forwarders from,
plus the Nodes/Pods informers that give each endpoint a topology
context.

Preference order (``ServingTopologyAware`` gate):

1. **same-slice consolidation** — endpoints in the slice already
   hosting the most replicas of this service come first (requests
   concentrate where the service is packed, which keeps OTHER slices'
   contiguous boxes cold and reclaimable);
2. **least-fragmented node** — within a slice, endpoints on nodes with
   the fewest free chips first (traffic prefers replicas that are not
   squatting on gang-usable space, so a defrag/scale-down naturally
   drains the expensive ones);
3. name, for determinism.

Dispatch is least-outstanding with preference tiebreak: at low load
the preferred endpoints carry everything; as load grows requests spill
down the order instead of queueing. With the gate off the order is
plain sorted names and dispatch is the same least-outstanding loop —
the legacy client-side balance, byte-identical in behavior.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..api import types as t
from ..client.informer import InformerFactory, SharedInformer
from ..metrics.registry import Counter, Gauge

log = logging.getLogger("serving-router")

ROUTER_ENDPOINTS = Gauge(
    "serving_router_endpoints",
    "Ready endpoints the router currently balances across",
    labels=("service",))

ROUTER_PICKS = Counter(
    "serving_router_picks_total",
    "Requests dispatched, by preference tier (0 = most preferred)",
    labels=("service", "tier"))


@dataclass(frozen=True)
class Endpoint:
    ip: str
    port: int
    pod: str = ""
    node: str = ""
    slice_id: str = ""

    @property
    def url(self) -> str:
        return f"http://{self.ip}:{self.port}"


class TopologyRouter:
    """One router per (namespace, service). ``start()`` spins shared
    informers (or rides a caller-provided factory); ``pick``/``done``
    bracket each request."""

    def __init__(self, client, service: str, namespace: str = "default",
                 factory: Optional[InformerFactory] = None):
        self.service = service
        self.namespace = namespace
        self._own_factory = factory is None
        self.factory = factory or InformerFactory(client)
        self.endpoints: Optional[SharedInformer] = None
        self.nodes: Optional[SharedInformer] = None
        self.pods: Optional[SharedInformer] = None
        #: endpoint -> outstanding request count (caller-maintained
        #: via pick/done).
        self._outstanding: dict[Endpoint, int] = {}
        #: Cached preference order, rebuilt on informer events — the
        #: per-request pick() must not re-walk endpoints x pods.
        self._order: Optional[list[Endpoint]] = None
        self._wired = False

    async def start(self) -> None:
        self.endpoints = self.factory.informer("endpoints")
        self.nodes = self.factory.informer("nodes")
        self.pods = self.factory.informer("pods")
        for inf in (self.endpoints, self.nodes, self.pods):
            inf.add_handlers(
                on_add=lambda _o: self._invalidate(),
                on_update=lambda _o, _n: self._invalidate(),
                on_delete=lambda _o: self._invalidate())
        self._wired = True
        self.factory.start_all()
        for inf in (self.endpoints, self.nodes, self.pods):
            await inf.wait_for_sync()

    def _invalidate(self) -> None:
        self._order = None

    async def stop(self) -> None:
        if self._own_factory:
            await self.factory.stop_all()

    # -- topology model ---------------------------------------------------

    @staticmethod
    def _gated() -> bool:
        from ..util.features import GATES
        return GATES.enabled("ServingTopologyAware")

    def _node_slice(self, node_name: str) -> str:
        node = self.nodes.get(node_name) if self.nodes else None
        topo = node.status.tpu if node is not None else None
        return topo.slice_id if topo is not None else ""

    def _free_chips_by_node(self, nodes: set[str]) -> dict[str, int]:
        """ONE pod-informer pass for every node of interest (per
        ranking rebuild, never per node or per request)."""
        used: dict[str, int] = {}
        for p in self.pods.list() if self.pods else []:
            n = p.spec.node_name
            if n in nodes and t.is_pod_active(p):
                used[n] = used.get(n, 0) + sum(
                    r.chip_count() for r in p.spec.tpu_resources)
        out = {}
        for n in nodes:
            node = self.nodes.get(n) if self.nodes else None
            if node is None:
                out[n] = 0
                continue
            cap = int(node.status.allocatable.get(t.RESOURCE_TPU, 0)
                      or node.status.capacity.get(t.RESOURCE_TPU, 0))
            out[n] = max(cap - used.get(n, 0), 0)
        return out

    def ranked(self) -> list[Endpoint]:
        """Current ready endpoints in preference order (the unit-tested
        core; pick() reads the event-invalidated cache of this)."""
        ep = self.endpoints.get(f"{self.namespace}/{self.service}") \
            if self.endpoints else None
        if ep is None:
            return []
        port = next((p.port for subset in ep.subsets
                     for p in subset.ports), 0)
        out = []
        for subset in ep.subsets:
            for a in subset.addresses:
                if not a.ip:
                    continue
                pod_name = (a.target_ref.name if a.target_ref is not None
                            else a.hostname)
                out.append(Endpoint(
                    ip=a.ip, port=port, pod=pod_name, node=a.node_name,
                    slice_id=self._node_slice(a.node_name)))
        if not self._gated():
            out.sort(key=lambda e: (e.pod, e.ip))
            return out
        by_slice: dict[str, int] = {}
        for e in out:
            by_slice[e.slice_id] = by_slice.get(e.slice_id, 0) + 1
        free = self._free_chips_by_node({e.node for e in out if e.node})
        out.sort(key=lambda e: (
            -by_slice.get(e.slice_id, 0),   # consolidated slice first
            e.slice_id,                     # stable among equals
            free.get(e.node, 0),            # least-fragmented node
            e.pod, e.ip))
        return out

    # -- dispatch ---------------------------------------------------------

    def pick(self) -> Optional[Endpoint]:
        """Least-outstanding endpoint, preference order breaking ties.
        Callers MUST pair with :meth:`done` when the request finishes.
        The ranking is cached and invalidated by informer events; an
        unwired router (tests injecting fake informers) re-ranks every
        time."""
        if self._order is None or not self._wired:
            self._order = self.ranked()
        order = self._order
        ROUTER_ENDPOINTS.set(float(len(order)), service=self.service)
        if not order:
            return None
        live = set(order)
        for e in list(self._outstanding):
            if e not in live and self._outstanding[e] <= 0:
                del self._outstanding[e]  # departed replica
        best_i, best = min(
            enumerate(order),
            key=lambda pair: (self._outstanding.get(pair[1], 0), pair[0]))
        self._outstanding[best] = self._outstanding.get(best, 0) + 1
        ROUTER_PICKS.inc(service=self.service, tier=str(best_i))
        return best

    def done(self, endpoint: Endpoint) -> None:
        n = self._outstanding.get(endpoint, 0)
        if n <= 1:
            self._outstanding.pop(endpoint, None)
        else:
            self._outstanding[endpoint] = n - 1
