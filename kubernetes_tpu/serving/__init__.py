"""Inference serving — the user-traffic half of the fleet.

Pieces (ISSUE 11 / ROADMAP item 2):

- :mod:`.autoscaler` — the pure HPA-analog decision engine the
  inference controller runs each tick over ``ClusterMonitor.latest()``
  rollups (tokens/s + busy fraction), with stabilization windows,
  per-step rate limits, and an explicit staleness refusal.
- :mod:`.router` — slice-topology-aware endpoint selection over the
  same Endpoints/Nodes/Pods informers the proxy uses: the client-side
  load balancer the serving loadgen (``perf/serving_bench.py``) and
  any in-cluster gateway balance requests with.

The API type lives in :mod:`kubernetes_tpu.api.serving`; the
reconciler in :mod:`kubernetes_tpu.controllers.inference`; the stub
token-generating server in :mod:`kubernetes_tpu.workloads.model_server`.
"""
