"""Multi-tenant job queueing: fair-share admission, quota borrowing,
and backfill for gang TPU jobs (the Kueue analog).

Layout:

- :mod:`~kubernetes_tpu.api.queueing` — ClusterQueue/LocalQueue kinds;
- :mod:`.fairshare` — pure DRF/borrow/backfill/reclaim decision math;
- :mod:`kubernetes_tpu.controllers.queue` — the QueueController
  driving it over informers;
- :mod:`.metrics` — the ``queue_*`` metric family;
- :mod:`.harness` — the two-tenant starvation/reclaim smoke shared by
  ``hack/queue_smoke.sh`` and the integration tier.
"""
from . import fairshare, metrics  # noqa: F401
