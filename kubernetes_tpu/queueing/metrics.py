"""Queueing metrics — the ``queue_*`` family.

Covered by the tpuvet metric-name pass fixtures like the batch/chaos
families; the admission-wait histogram retains raw samples so the gang
bench's ``--queued`` stanza reports true percentiles, not bucket edges.
"""
from ..metrics.registry import Counter, Gauge, Histogram

_WAIT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0, 1800.0)

QUEUE_PENDING = Gauge(
    "queue_pending_gangs",
    "Gangs waiting for admission per ClusterQueue",
    labels=("queue",))

QUEUE_ADMITTED = Gauge(
    "queue_admitted_gangs",
    "Gangs currently admitted per ClusterQueue",
    labels=("queue",))

QUEUE_BORROWED = Gauge(
    "queue_borrowed_resources",
    "Usage above nominal quota (lent by the cohort) per queue+resource",
    labels=("queue", "resource"))

QUEUE_USAGE = Gauge(
    "queue_resource_usage",
    "Admitted usage per ClusterQueue and resource",
    labels=("queue", "resource"))

ADMISSION_WAIT = Histogram(
    "queue_admission_wait_seconds",
    "PodGroup create to admission latency",
    buckets=_WAIT_BUCKETS,
    # Raw samples: the --queued gang bench reports true p50/p99.
    sample_limit=100_000)

ADMISSIONS = Counter(
    "queue_admissions_total",
    "Gang admissions by queue and mode (Nominal|Borrowed|Backfill)",
    labels=("queue", "mode"))

RECLAIMS = Counter(
    "queue_reclaimed_gangs_total",
    "Borrowed gangs preempted back to pending when the lender's demand "
    "returned, per (victim) queue",
    labels=("queue",))
