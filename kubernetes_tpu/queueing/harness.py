"""Two-tenant queueing smoke — the acceptance scenario, shared.

One run drives the whole admission story over an in-process control
plane (LocalClient + Scheduler + QueueController, one 4x4x4 slice):

1. tenant A floods 10 gangs (80 chips demand) into a 32-chip nominal
   quota — fair-share admission lets it borrow tenant B's idle quota
   up to the 64-chip cohort, leaving a pending backlog;
2. tenant B submits ONE gang — its nominal quota is occupied by A's
   borrowing, so the controller reclaims (cheapest borrowed A gang
   unadmitted, bound pods evicted) and B's gang reaches Bound while
   A's backlog is still pending;
3. the reclaimed gang is requeued, not orphaned: it survives as a
   pending PodGroup and re-enters the DRF order.

Shared by ``hack/queue_smoke.sh`` (<60s CI gate) and
``tests/integration/test_queueing.py`` so the CI arm and the test tier
exercise one scenario, not two drifting copies. Raises AssertionError
on any violation; returns a report dict.
"""
from __future__ import annotations

import asyncio
import time

from ..api import types as t
from ..api.meta import ObjectMeta
from ..api.queueing import ClusterQueue, ClusterQueueSpec, LocalQueue, \
    LocalQueueSpec
from ..apiserver.admission import default_chain
from ..apiserver.registry import Registry
from ..client.informer import InformerFactory
from ..client.local import LocalClient
from ..controllers.queue import QueueController
from ..scheduler.scheduler import Scheduler
from ..util.features import GATES

CHIPS_PER_HOST = 4
GANG_SHAPE = [2, 2, 2]  # 8 chips -> 2 pods x 4 chips


def make_queues(nominal_chips: float = 32.0) -> list:
    """Two tenants, one borrowing cohort, half the slice each."""
    objs = []
    for tenant in ("a", "b"):
        objs.append(ClusterQueue(
            metadata=ObjectMeta(name=f"team-{tenant}"),
            spec=ClusterQueueSpec(
                cohort="main",
                nominal_quota={t.RESOURCE_TPU: nominal_chips})))
        objs.append(t.Namespace(metadata=ObjectMeta(name=f"tenant-{tenant}")))
        objs.append(LocalQueue(
            metadata=ObjectMeta(name=f"queue-{tenant}",
                                namespace=f"tenant-{tenant}"),
            spec=LocalQueueSpec(cluster_queue=f"team-{tenant}")))
    return objs


def make_gang(name: str, namespace: str, queue: str, priority: int = 0,
              shape: list = None, chips_per_pod: int = CHIPS_PER_HOST,
              runtime: float = None) -> tuple:
    """A queued gang + its member pods. ``shape``/``chips_per_pod``
    size it (default: one GANG_SHAPE box, host-sized pods);
    ``runtime`` stamps the backfill projection annotation."""
    shape = list(shape) if shape is not None else list(GANG_SHAPE)
    members = 1
    for d in shape:
        members *= d
    members //= chips_per_pod
    group = t.PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=t.PodGroupSpec(min_member=members, slice_shape=shape,
                            queue=queue,
                            priority=priority or None))
    if runtime is not None:
        from ..api.queueing import RUNTIME_ANNOTATION
        group.metadata.annotations[RUNTIME_ANNOTATION] = str(runtime)
    pods = []
    for m in range(members):
        pod = t.Pod(metadata=ObjectMeta(name=f"{name}-{m}",
                                        namespace=namespace),
                    spec=t.PodSpec(containers=[t.Container(
                        name="c", image="train",
                        resources=t.ResourceRequirements(
                            requests={"cpu": 0.5}),
                        tpu_requests=["tpu"])]))
        pod.spec.tpu_resources = [t.PodTpuRequest(name="tpu",
                                                  chips=chips_per_pod)]
        pod.spec.gang = name
        if priority:
            pod.spec.priority = priority
        pods.append(pod)
    return group, pods


async def _wait(predicate, deadline: float, what: str) -> None:
    loop = asyncio.get_running_loop()
    while not predicate():
        if loop.time() > deadline:
            raise AssertionError(f"queue smoke timeout: {what}")
        await asyncio.sleep(0.05)


async def run_queue_smoke(timeout: float = 30.0,
                          flood: int = 10) -> dict:
    """The scripted scenario (see module docstring)."""
    t0 = time.perf_counter()
    was_on = GATES.enabled("JobQueueing")
    # Everything after the flip sits inside the try: an exception in
    # setup must not leak the process-global gate on.
    GATES.set("JobQueueing", True)
    sched = qc = factory = None
    try:
        reg = Registry()
        reg.admission = default_chain(reg)
        reg.create(t.Namespace(metadata=ObjectMeta(name="default")))
        from ..perf.gang_bench import build_slice
        build_slice(reg, 0)  # 4x4x4 = 64 chips over 16 hosts
        client = LocalClient(reg)
        for obj in make_queues(nominal_chips=32.0):
            reg.create(obj)
        factory = InformerFactory(client)
        sched = Scheduler(client, backoff_seconds=0.2,
                          informer_factory=factory)
        qc = QueueController(client, factory, fits_probe=lambda g: True)
        loop = asyncio.get_running_loop()
        await sched.start()
        await qc.start()

        def bound_gangs(ns: str) -> set:
            pods, _ = reg.list("pods", ns)
            out: dict = {}
            for p in pods:
                if p.spec.node_name and t.is_pod_active(p):
                    out.setdefault(p.spec.gang, 0)
                    out[p.spec.gang] += 1
            return {g for g, n in out.items() if n >= 2}

        def groups(ns: str) -> list:
            gs, _ = reg.list("podgroups", ns)
            return gs

        # Phase 1: tenant A floods. Nominal 32 + borrow up to the
        # 64-chip cohort -> exactly 8 of the 10 gangs admit and bind.
        for i in range(flood):
            group, pods = make_gang(f"flood-{i:02d}", "tenant-a", "queue-a")
            await client.create(group)
            for pod in pods:
                await client.create(pod)
        await _wait(lambda: len(bound_gangs("tenant-a")) >= 8,
                    loop.time() + timeout, "tenant A's 8 gangs bound")
        a_admitted = [g for g in groups("tenant-a") if g.status.admitted]
        a_pending = [g for g in groups("tenant-a") if not g.status.admitted]
        assert len(a_admitted) == 8, f"A admitted {len(a_admitted)} != 8"
        assert len(a_pending) == flood - 8
        borrowed_modes = [g.status.admission_mode for g in a_admitted]
        assert borrowed_modes.count("Borrowed") == 4, (
            f"expected 4 borrowed admissions, got {borrowed_modes}")

        # Phase 2: tenant B's single gang forces reclaim.
        group, pods = make_gang("bee-00", "tenant-b", "queue-b")
        await client.create(group)
        for pod in pods:
            await client.create(pod)
        await _wait(lambda: "bee-00" in bound_gangs("tenant-b"),
                    loop.time() + timeout, "tenant B's gang bound")

        # Reclaim happened: exactly one borrowed A gang back to pending,
        # requeued not orphaned; A's backlog still pending.
        a_groups = groups("tenant-a")
        a_admitted = [g for g in a_groups if g.status.admitted]
        a_pending = [g for g in a_groups if not g.status.admitted]
        assert len(a_groups) == flood, "reclaim orphaned a PodGroup"
        assert len(a_admitted) == 7, f"A admitted {len(a_admitted)} != 7"
        assert len(a_pending) == flood - 7
        reclaimed = [g for g in a_pending
                     if any(p.metadata.deletion_timestamp is not None
                            for p in reg.list("pods", "tenant-a")[0]
                            if p.spec.gang == g.metadata.name)]
        assert reclaimed, "no gang shows evicted members (reclaim missing)"
        for g in a_pending:
            assert g.status.phase == t.PODGROUP_PENDING
            assert g.status.admission_mode == ""

        # Conservation: cohort usage never exceeds cohort nominal.
        usage = sum(8.0 for g in a_admitted) + 8.0
        assert usage <= 64.0 + 1e-9, f"cohort over-committed: {usage}"

        # Queue statuses converged (controller publishes counts).
        await _wait(
            lambda: (reg.get("clusterqueues", "", "team-b").status.admitted
                     == 1),
            loop.time() + timeout, "team-b status.admitted == 1")
        cq_a = reg.get("clusterqueues", "", "team-a")
        return {
            "a_admitted": len(a_admitted),
            "a_pending": len(a_pending),
            "b_bound": True,
            "reclaimed_gangs": len(reclaimed),
            "team_a_borrowed": dict(cq_a.status.borrowed),
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    finally:
        if qc is not None:
            await qc.stop()
        if sched is not None:
            await sched.stop()
        if factory is not None:
            await factory.stop_all()  # last: the scheduler rides it too
        if not was_on:
            GATES.set("JobQueueing", False)


def run_queue_smoke_schedules(base_seed, schedules: int = 4,
                              mode: str = "dpor",
                              timeout: float = 30.0) -> dict:
    """The tpusan arm of the queueing gate: the same two-tenant
    admission story explored under ``schedules`` seeded interleavings
    with the invariant sanitizer armed — the DRF/borrow/reclaim path
    must hold conservation and monotonicity on EVERY schedule, not just
    the one the event loop happens to produce. Raises on any scenario
    assert or invariant violation (the tpusan seed replays it)."""
    from ..analysis import interleave

    rep = interleave.explore_sanitized(
        lambda i: run_queue_smoke(timeout=timeout),
        base_seed=base_seed, schedules=schedules, mode=mode,
        extract=lambda v: {"reclaimed_gangs": v["reclaimed_gangs"]})
    rep["base_seed"] = base_seed
    return rep
