"""Two-tenant queueing smoke — the acceptance scenario, shared.

One run drives the whole admission story over an in-process control
plane (LocalClient + Scheduler + QueueController, one 4x4x4 slice):

1. tenant A floods 10 gangs (80 chips demand) into a 32-chip nominal
   quota — fair-share admission lets it borrow tenant B's idle quota
   up to the 64-chip cohort, leaving a pending backlog;
2. tenant B submits ONE gang — its nominal quota is occupied by A's
   borrowing, so the controller reclaims (cheapest borrowed A gang
   unadmitted, bound pods evicted) and B's gang reaches Bound while
   A's backlog is still pending;
3. the reclaimed gang is requeued, not orphaned: it survives as a
   pending PodGroup and re-enters the DRF order.

Shared by ``hack/queue_smoke.sh`` (<60s CI gate) and
``tests/integration/test_queueing.py`` so the CI arm and the test tier
exercise one scenario, not two drifting copies. Raises AssertionError
on any violation; returns a report dict.
"""
from __future__ import annotations

import asyncio
import time

from ..api import errors, types as t
from ..api.meta import ObjectMeta
from ..api.queueing import ClusterQueue, ClusterQueueSpec, LocalQueue, \
    LocalQueueSpec
from ..apiserver.admission import default_chain
from ..apiserver.registry import Registry
from ..client.informer import InformerFactory
from ..client.local import LocalClient
from ..controllers.queue import QueueController
from ..scheduler.scheduler import Scheduler
from ..util.features import GATES

CHIPS_PER_HOST = 4
GANG_SHAPE = [2, 2, 2]  # 8 chips -> 2 pods x 4 chips


def make_queues(nominal_chips: float = 32.0) -> list:
    """Two tenants, one borrowing cohort, half the slice each."""
    objs = []
    for tenant in ("a", "b"):
        objs.append(ClusterQueue(
            metadata=ObjectMeta(name=f"team-{tenant}"),
            spec=ClusterQueueSpec(
                cohort="main",
                nominal_quota={t.RESOURCE_TPU: nominal_chips})))
        objs.append(t.Namespace(metadata=ObjectMeta(name=f"tenant-{tenant}")))
        objs.append(LocalQueue(
            metadata=ObjectMeta(name=f"queue-{tenant}",
                                namespace=f"tenant-{tenant}"),
            spec=LocalQueueSpec(cluster_queue=f"team-{tenant}")))
    return objs


def make_gang(name: str, namespace: str, queue: str, priority: int = 0,
              shape: list = None, chips_per_pod: int = CHIPS_PER_HOST,
              runtime: float = None, members: int = None,
              checkpoint_grace: float = None,
              elastic: tuple = None, resources: dict = None) -> tuple:
    """A queued gang + its member pods. ``shape``/``chips_per_pod``
    size it (default: one GANG_SHAPE box, host-sized pods);
    ``runtime`` stamps the backfill projection annotation.

    Graceful-preemption extensions: ``members`` sizes a SHAPELESS gang
    (pass ``resources`` for its quota demand — compact allocation, no
    contiguity constraint), ``checkpoint_grace`` opts it into the
    signal→checkpoint→requeue protocol, ``elastic=(min, max)`` makes
    it elastic (min_member = min: the gang must stay releasable at its
    shrunken size)."""
    shape = list(shape) if shape is not None else (
        list(GANG_SHAPE) if members is None else [])
    if members is None:
        members = 1
        for d in shape:
            members *= d
        members //= chips_per_pod
    group = t.PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=t.PodGroupSpec(min_member=members, slice_shape=shape,
                            queue=queue,
                            priority=priority or None,
                            resources=dict(resources or {})))
    if checkpoint_grace is not None:
        group.spec.checkpoint = t.CheckpointSpec(
            grace_seconds=checkpoint_grace)
    if elastic is not None:
        group.spec.min_replicas, group.spec.max_replicas = elastic
        group.spec.min_member = elastic[0]
    if runtime is not None:
        from ..api.queueing import RUNTIME_ANNOTATION
        group.metadata.annotations[RUNTIME_ANNOTATION] = str(runtime)
    pods = []
    for m in range(members):
        pod = t.Pod(metadata=ObjectMeta(name=f"{name}-{m}",
                                        namespace=namespace),
                    spec=t.PodSpec(containers=[t.Container(
                        name="c", image="train",
                        resources=t.ResourceRequirements(
                            requests={"cpu": 0.5}),
                        tpu_requests=["tpu"])]))
        pod.spec.tpu_resources = [t.PodTpuRequest(name="tpu",
                                                  chips=chips_per_pod)]
        pod.spec.gang = name
        if priority:
            pod.spec.priority = priority
        pods.append(pod)
    return group, pods


async def _wait(predicate, deadline: float, what: str) -> None:
    loop = asyncio.get_running_loop()
    while not predicate():
        if loop.time() > deadline:
            raise AssertionError(f"queue smoke timeout: {what}")
        await asyncio.sleep(0.05)


async def run_queue_smoke(timeout: float = 30.0,
                          flood: int = 10) -> dict:
    """The scripted scenario (see module docstring)."""
    t0 = time.perf_counter()
    was_on = GATES.enabled("JobQueueing")
    # Everything after the flip sits inside the try: an exception in
    # setup must not leak the process-global gate on.
    GATES.set("JobQueueing", True)
    sched = qc = factory = None
    try:
        reg = Registry()
        reg.admission = default_chain(reg)
        reg.create(t.Namespace(metadata=ObjectMeta(name="default")))
        from ..perf.gang_bench import build_slice
        build_slice(reg, 0)  # 4x4x4 = 64 chips over 16 hosts
        client = LocalClient(reg)
        for obj in make_queues(nominal_chips=32.0):
            reg.create(obj)
        factory = InformerFactory(client)
        sched = Scheduler(client, backoff_seconds=0.2,
                          informer_factory=factory)
        qc = QueueController(client, factory, fits_probe=lambda g: True)
        loop = asyncio.get_running_loop()
        await sched.start()
        await qc.start()

        def bound_gangs(ns: str) -> set:
            pods, _ = reg.list("pods", ns)
            out: dict = {}
            for p in pods:
                if p.spec.node_name and t.is_pod_active(p):
                    out.setdefault(p.spec.gang, 0)
                    out[p.spec.gang] += 1
            return {g for g, n in out.items() if n >= 2}

        def groups(ns: str) -> list:
            gs, _ = reg.list("podgroups", ns)
            return gs

        # Phase 1: tenant A floods. Nominal 32 + borrow up to the
        # 64-chip cohort -> exactly 8 of the 10 gangs admit and bind.
        for i in range(flood):
            group, pods = make_gang(f"flood-{i:02d}", "tenant-a", "queue-a")
            await client.create(group)
            for pod in pods:
                await client.create(pod)
        await _wait(lambda: len(bound_gangs("tenant-a")) >= 8,
                    loop.time() + timeout, "tenant A's 8 gangs bound")
        a_admitted = [g for g in groups("tenant-a") if g.status.admitted]
        a_pending = [g for g in groups("tenant-a") if not g.status.admitted]
        assert len(a_admitted) == 8, f"A admitted {len(a_admitted)} != 8"
        assert len(a_pending) == flood - 8
        borrowed_modes = [g.status.admission_mode for g in a_admitted]
        assert borrowed_modes.count("Borrowed") == 4, (
            f"expected 4 borrowed admissions, got {borrowed_modes}")

        # Phase 2: tenant B's single gang forces reclaim.
        group, pods = make_gang("bee-00", "tenant-b", "queue-b")
        await client.create(group)
        for pod in pods:
            await client.create(pod)
        await _wait(lambda: "bee-00" in bound_gangs("tenant-b"),
                    loop.time() + timeout, "tenant B's gang bound")

        # Reclaim happened: exactly one borrowed A gang back to pending,
        # requeued not orphaned; A's backlog still pending.
        a_groups = groups("tenant-a")
        a_admitted = [g for g in a_groups if g.status.admitted]
        a_pending = [g for g in a_groups if not g.status.admitted]
        assert len(a_groups) == flood, "reclaim orphaned a PodGroup"
        assert len(a_admitted) == 7, f"A admitted {len(a_admitted)} != 7"
        assert len(a_pending) == flood - 7
        reclaimed = [g for g in a_pending
                     if any(p.metadata.deletion_timestamp is not None
                            for p in reg.list("pods", "tenant-a")[0]
                            if p.spec.gang == g.metadata.name)]
        assert reclaimed, "no gang shows evicted members (reclaim missing)"
        for g in a_pending:
            assert g.status.phase == t.PODGROUP_PENDING
            assert g.status.admission_mode == ""

        # Conservation: cohort usage never exceeds cohort nominal.
        usage = sum(8.0 for g in a_admitted) + 8.0
        assert usage <= 64.0 + 1e-9, f"cohort over-committed: {usage}"

        # Queue statuses converged (controller publishes counts).
        await _wait(
            lambda: (reg.get("clusterqueues", "", "team-b").status.admitted
                     == 1),
            loop.time() + timeout, "team-b status.admitted == 1")
        cq_a = reg.get("clusterqueues", "", "team-a")
        return {
            "a_admitted": len(a_admitted),
            "a_pending": len(a_pending),
            "b_bound": True,
            "reclaimed_gangs": len(reclaimed),
            "team_a_borrowed": dict(cq_a.status.borrowed),
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    finally:
        if qc is not None:
            await qc.stop()
        if sched is not None:
            await sched.stop()
        if factory is not None:
            await factory.stop_all()  # last: the scheduler rides it too
        if not was_on:
            GATES.set("JobQueueing", False)


async def run_preempt_smoke(seed: int = 0, timeout: float = 45.0) -> dict:
    """Graceful-preemption acceptance scenario (<60s): signal →
    checkpoint → shrink → regrow → converge, with a seeded
    mid-checkpoint member crash.

    One 64-chip slice, two tenants in a cohort (32 nominal each), the
    JobQueueing + GracefulPreemption gates on:

    1. tenant A runs ONE elastic, checkpoint-opted gang at full size
       (16 members / 64 chips — 32 borrowed from B);
    2. a simulated workload watches for the Signaled phase and reports
       deterministic checkpoint steps (100 per round) for each
       signaled member;
    3. tenant B submits a fixed 32-chip gang: reclaim SHRINKS A to
       min_replicas (8) instead of unadmitting it — the surplus
       members are signaled, checkpoint, and only then evicted; the
       ``preempt`` chaos site kills one signaled member between
       signal and marker (the protocol must converge anyway);
    4. B finishes (deleted); the regrow pass raises A's target back
       to 16 and the recreated members bind — convergence.

    Deterministic extract (step numbers, member counts, phases) lets
    ``run_preempt_smoke_schedules`` assert byte-identical convergence
    across explored interleavings. Shared by ``hack/preempt_smoke.sh``
    and the integration tier."""
    from .. import preemption as gp
    from ..chaos import core as chaos

    t0 = time.perf_counter()
    was_q = GATES.enabled("JobQueueing")
    was_g = GATES.enabled("GracefulPreemption")
    GATES.set("JobQueueing", True)
    GATES.set("GracefulPreemption", True)
    controller = chaos.arm(chaos.ChaosController(int(seed), ()))
    controller.trigger(chaos.SITE_PREEMPT, "kill-member")
    sched = qc = factory = None
    reporter = None
    try:
        reg = Registry()
        reg.admission = default_chain(reg)
        reg.create(t.Namespace(metadata=ObjectMeta(name="default")))
        from ..perf.gang_bench import build_slice
        build_slice(reg, 0)  # 4x4x4 = 64 chips over 16 hosts
        client = LocalClient(reg)
        for obj in make_queues(nominal_chips=32.0):
            reg.create(obj)
        factory = InformerFactory(client)
        sched = Scheduler(client, backoff_seconds=0.2,
                          informer_factory=factory)
        qc = QueueController(client, factory, fits_probe=lambda g: True)
        loop = asyncio.get_running_loop()
        await sched.start()
        await qc.start()

        async def simulated_workload():
            """The gang's training side: a member checkpoints only
            when its own DELIVERED signal (the pod annotation) exists
            and it is still alive — a chaos-killed member can never
            publish a marker. Steps are deterministic (100/round)."""
            while True:
                groups, _ = reg.list("podgroups", "")
                for g in groups:
                    st = g.status.preemption
                    if st is None or st.phase not in (
                            t.PREEMPT_SIGNALED, t.PREEMPT_CHECKPOINTING):
                        continue
                    step = 100 * (st.rounds + 1)
                    for member in st.signaled:
                        if member in st.checkpointed:
                            continue
                        try:
                            pod = reg.get("pods", g.metadata.namespace,
                                          member)
                        except errors.NotFoundError:
                            continue
                        if not t.is_pod_active(pod) or not \
                                pod.metadata.annotations.get(
                                    t.PREEMPT_ANNOTATION):
                            continue
                        await gp.record_member_checkpoint(
                            client, g.metadata.namespace,
                            g.metadata.name, member, step)
                await asyncio.sleep(0.05)

        reporter = asyncio.create_task(simulated_workload())

        def bound_members(ns: str, gang: str) -> list:
            pods, _ = reg.list("pods", ns)
            return [p for p in pods if p.spec.gang == gang
                    and p.spec.node_name and t.is_pod_active(p)]

        # Phase 1: A's elastic gang fills the slice (Borrowed mode).
        group, pods = make_gang("ela-00", "tenant-a", "queue-a",
                                shape=[4, 4, 4], checkpoint_grace=10.0,
                                elastic=(8, 16))
        await client.create(group)
        for pod in pods:
            await client.create(pod)
        await _wait(lambda: len(bound_members("tenant-a", "ela-00")) >= 16,
                    loop.time() + timeout / 3, "A's 16 members bound")

        # Phase 2: B's fixed gang forces the reclaim storm — A shrinks.
        bgroup, bpods = make_gang(
            "bee-00", "tenant-b", "queue-b", members=8,
            resources={t.RESOURCE_TPU: 32.0})
        await client.create(bgroup)
        for pod in bpods:
            await client.create(pod)
        await _wait(lambda: len(bound_members("tenant-b", "bee-00")) >= 8,
                    loop.time() + timeout / 2, "B's gang bound after shrink")
        await _wait(lambda: len(bound_members("tenant-a", "ela-00")) == 8,
                    loop.time() + timeout / 2, "A shrunk to 8 members")
        a = reg.get("podgroups", "tenant-a", "ela-00")
        assert a.status.admitted, "shrink must keep the gang admitted"
        assert a.status.replicas == 8, a.status.replicas
        st = a.status.preemption
        assert st is not None and st.phase == t.PREEMPT_REQUEUED, st
        assert st.checkpoint_step == 100, st.checkpoint_step
        assert st.outcome == "checkpointed", st.outcome
        crash_kills = sum(1 for f in controller.injected
                          if f.site == chaos.SITE_PREEMPT)
        assert crash_kills == 1, "mid-checkpoint crash never fired"
        # The crashed member reported nothing; the others did. 8
        # surplus were signaled, one was chaos-killed mid-checkpoint.
        assert len(st.signaled) == 8 and len(st.checkpointed) == 7, (
            st.signaled, st.checkpointed)

        # Phase 3: B finishes; A regrows to max and re-fills the slice
        # (the evicted members' controller-recreated replacements).
        for pod in bpods:
            try:
                await client.delete("pods", "tenant-b",
                                    pod.metadata.name,
                                    grace_period_seconds=0)
            except errors.NotFoundError:
                pass
        await client.delete("podgroups", "tenant-b", "bee-00")
        for m in range(16, 24):  # fresh names: the old ones linger
            pod = make_gang("ela-00", "tenant-a", "queue-a",
                            shape=[4, 4, 4])[1][0]
            pod.metadata.name = f"ela-00-{m}"
            await client.create(pod)
        await _wait(lambda: (reg.get("podgroups", "tenant-a", "ela-00")
                             .status.replicas == 16),
                    loop.time() + timeout, "A regrown to 16")
        await _wait(lambda: len(bound_members("tenant-a", "ela-00")) >= 16,
                    loop.time() + timeout, "A re-filled the slice")
        a = reg.get("podgroups", "tenant-a", "ela-00")
        return {
            "a_admitted": a.status.admitted,
            "a_replicas": a.status.replicas,
            "a_bound": len(bound_members("tenant-a", "ela-00")),
            "shrink_outcome": st.outcome,
            "checkpoint_step": st.checkpoint_step,
            "signaled": len(st.signaled),
            "checkpointed": len(st.checkpointed),
            "crash_kills": crash_kills,
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    finally:
        chaos.disarm()
        if reporter is not None:
            reporter.cancel()
        if qc is not None:
            await qc.stop()
        if sched is not None:
            await sched.stop()
        if factory is not None:
            await factory.stop_all()  # last: the scheduler rides it too
        if not was_q:
            GATES.set("JobQueueing", False)
        if not was_g:
            GATES.set("GracefulPreemption", False)


def run_preempt_smoke_schedules(base_seed, schedules: int = 4,
                                mode: str = "dpor",
                                timeout: float = 45.0) -> dict:
    """tpusan arm of the graceful-preemption gate: the same seeded
    storm explored under ``schedules`` interleavings with the cluster
    invariants armed (incl. checkpoint-monotonic), asserting the
    DETERMINISTIC convergence facts are byte-identical on every
    schedule."""
    from ..analysis import interleave

    keys = ("a_admitted", "a_replicas", "a_bound", "shrink_outcome",
            "checkpoint_step", "signaled", "checkpointed", "crash_kills")
    rep = interleave.explore_sanitized(
        lambda i: run_preempt_smoke(seed=int(base_seed) if str(
            base_seed).isdigit() else 0, timeout=timeout),
        base_seed=base_seed, schedules=schedules, mode=mode,
        extract=lambda v: {k: v[k] for k in keys})
    outcomes = [{k: r[k] for k in keys} for r in rep["schedules"]]
    assert all(o == outcomes[0] for o in outcomes), (
        f"convergence diverged across schedules: {outcomes}")
    rep["base_seed"] = base_seed
    return rep


async def _migrate_stack(reg, client, factory, interval: float = 0.3):
    """Scheduler + queue controller + migration controller wired the
    way the single-binary composer does it (the controller reads the
    LIVE scheduler cache through the probe)."""
    from ..controllers.migrate import MigrationController
    sched = Scheduler(client, backoff_seconds=0.2,
                      informer_factory=factory)
    qc = QueueController(client, factory, fits_probe=lambda g: True)
    mc = MigrationController(client, factory,
                             cache_probe=lambda: sched.cache,
                             interval=interval, max_concurrent=1,
                             cooldown_seconds=0.0,
                             round_timeout_seconds=30.0)
    await sched.start()
    await qc.start()
    await mc.start()
    return sched, qc, mc


def _member_keeper(reg, client, gang_size: dict):
    """The TrainJob-controller stand-in: tops each tracked gang back up
    to its target size with FRESH-named members after an eviction (the
    preempt-smoke phase-3 step, continuous), and answers preemption
    signals with deterministic checkpoint markers (100/round)."""
    from .. import preemption as gp

    async def task():
        serial = 0
        while True:
            groups, _ = reg.list("podgroups", "")
            for g in groups:
                st = g.status.preemption
                if st is not None and st.phase in (
                        t.PREEMPT_SIGNALED, t.PREEMPT_CHECKPOINTING):
                    step = 100 * (st.rounds + 1)
                    for member in st.signaled:
                        if member in st.checkpointed:
                            continue
                        try:
                            pod = reg.get("pods", g.metadata.namespace,
                                          member)
                        except errors.NotFoundError:
                            continue
                        if not t.is_pod_active(pod) or not \
                                pod.metadata.annotations.get(
                                    t.PREEMPT_ANNOTATION):
                            continue
                        await gp.record_member_checkpoint(
                            client, g.metadata.namespace,
                            g.metadata.name, member, step)
            for gname, (ns, queue, want) in gang_size.items():
                pods, _ = reg.list("pods", ns)
                live = [p for p in pods
                        if p.spec.gang == gname and t.is_pod_active(p)
                        and p.metadata.deletion_timestamp is None]
                for _ in range(want - len(live)):
                    serial += 1
                    pod = make_gang(gname, ns, queue)[1][0]
                    pod.metadata.name = f"{gname}-r{serial}"
                    await client.create(pod)
            await asyncio.sleep(0.05)

    return asyncio.create_task(task())


async def run_migrate_smoke(seed: int = 0, timeout: float = 60.0) -> dict:
    """Live-migration evacuation acceptance (<90s): a bound gang's host
    goes degraded -> reserve-then-move gets the gang off the sick chips
    with its checkpoint intact, never a hard evict.

    One 64-chip slice, the GangLiveMigration + GracefulPreemption
    gates on:

    1. a 2x2x2 checkpoint-opted gang binds (2 members, 2 hosts);
    2. one of its hosts gets the kmon degraded taint (the harness
       plays the alert->taint pipeline's part directly);
    3. the migration controller reserves a target box OFF the sick
       host, then signals through the preemption engine; the seeded
       ``migrate`` chaos site crashes the controller mid-round (the
       next sweep must resume purely from status.migration + cache);
    4. members checkpoint, evict, and the recreated members bind onto
       the reserved box — round closes ``moved``, nothing remains on
       the degraded host, checkpoint_step > 0.

    Deterministic extract lets ``run_migrate_smoke_schedules`` assert
    byte-identical convergence across explored interleavings. Shared
    by ``hack/migrate_smoke.sh`` and the integration tier."""
    from ..api.meta import now as meta_now
    from ..api.scheme import deepcopy
    from ..chaos import core as chaos
    from ..monitoring.rules import TAINT_DEGRADED

    t0 = time.perf_counter()
    gates = ("JobQueueing", "GracefulPreemption", "GangLiveMigration")
    was = {g: GATES.enabled(g) for g in gates}
    for g in gates:
        GATES.set(g, True)
    controller = chaos.arm(chaos.ChaosController(int(seed), ()))
    controller.trigger(chaos.SITE_MIGRATE, "crash-mid-round")
    sched = qc = mc = factory = keeper = None
    try:
        reg = Registry()
        reg.admission = default_chain(reg)
        reg.create(t.Namespace(metadata=ObjectMeta(name="default")))
        from ..perf.gang_bench import build_slice
        build_slice(reg, 0)  # 4x4x4 = 64 chips over 16 hosts
        client = LocalClient(reg)
        for obj in make_queues(nominal_chips=64.0):
            reg.create(obj)
        factory = InformerFactory(client)
        sched, qc, mc = await _migrate_stack(reg, client, factory)
        loop = asyncio.get_running_loop()
        gang_size: dict = {}
        keeper = _member_keeper(reg, client, gang_size)

        def bound_members(ns: str, gang: str) -> list:
            pods, _ = reg.list("pods", ns)
            return [p for p in pods if p.spec.gang == gang
                    and p.spec.node_name and t.is_pod_active(p)]

        def migration(ns: str, gang: str):
            return reg.get("podgroups", ns, gang).status.migration

        # Phase 1: the gang binds.
        group, pods = make_gang("eva-00", "tenant-a", "queue-a",
                                checkpoint_grace=10.0)
        await client.create(group)
        for pod in pods:
            await client.create(pod)
        gang_size["eva-00"] = ("tenant-a", "queue-a", len(pods))
        await _wait(lambda: len(bound_members("tenant-a", "eva-00")) >= 2,
                    loop.time() + timeout / 3, "eva gang bound")

        # Phase 2: one of its hosts goes degraded (what the kmon
        # alert->taint pipeline does on TpuChipSick).
        victim = sorted(p.spec.node_name
                        for p in bound_members("tenant-a", "eva-00"))[0]
        node = deepcopy(reg.get("nodes", "", victim))
        node.spec.taints.append(t.Taint(
            key=TAINT_DEGRADED, value="TpuChipSick", effect="NoSchedule",
            time_added=meta_now()))
        await client.update(node)

        # Phase 3: reserve-then-move runs to completion (surviving the
        # seeded mid-round controller crash).
        await _wait(lambda: (migration("tenant-a", "eva-00") is not None
                             and migration("tenant-a", "eva-00").outcome
                             == "moved"),
                    loop.time() + timeout, "migration round closed moved")
        await _wait(lambda: len(bound_members("tenant-a", "eva-00")) >= 2
                    and all(p.spec.node_name != victim
                            for p in bound_members("tenant-a", "eva-00")),
                    loop.time() + timeout, "gang re-bound off sick host")
        g = reg.get("podgroups", "tenant-a", "eva-00")
        mig = g.status.migration
        st = g.status.preemption
        assert mig.rounds == 1, mig.rounds
        assert mig.reason == t.MIGRATE_REASON_DEGRADED, mig.reason
        assert st is not None and st.checkpoint_step > 0, st
        crash_faults = sum(1 for f in controller.injected
                           if f.site == chaos.SITE_MIGRATE)
        assert crash_faults == 1, "mid-round crash never fired"
        return {
            "outcome": mig.outcome,
            "reason": mig.reason,
            "rounds": mig.rounds,
            "checkpoint_step": st.checkpoint_step,
            "bound": len(bound_members("tenant-a", "eva-00")),
            "off_sick_host": all(
                p.spec.node_name != victim
                for p in bound_members("tenant-a", "eva-00")),
            "crash_faults": crash_faults,
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    finally:
        chaos.disarm()
        if keeper is not None:
            keeper.cancel()
        if mc is not None:
            await mc.stop()
        if qc is not None:
            await qc.stop()
        if sched is not None:
            await sched.stop()
        if factory is not None:
            await factory.stop_all()  # last: the scheduler rides it too
        for g, on in was.items():
            if not on:
                GATES.set(g, False)


async def run_defrag_smoke(seed: int = 0, timeout: float = 60.0) -> dict:
    """Defragmentation acceptance: a large pending gang fits nowhere
    until the planner consolidates a small donor gang, scored by the
    gain in ``largest_free_box_volume``.

    Two 64-chip slices:

    1. a 4x4x2 pin gang (not checkpoint-opted -> never a donor) takes
       half of slice-000; a 2x2x2 checkpoint-opted donor is steered
       onto slice-001 (node selector — scaffolding that stands in for
       historical placement; its recreated members carry none);
    2. a full-slice 4x4x4 gang arrives: blocked on both slices;
    3. the defrag planner moves the donor onto slice-000's free half
       (gain: slice-001 becomes one solid 64-box), the blocked gang
       binds there — time-to-placement for the big gang is the
       migration, not an operator page."""
    from ..api.scheme import deepcopy
    from ..chaos import core as chaos

    t0 = time.perf_counter()
    gates = ("JobQueueing", "GracefulPreemption", "GangLiveMigration")
    was = {g: GATES.enabled(g) for g in gates}
    for g in gates:
        GATES.set(g, True)
    chaos.arm(chaos.ChaosController(int(seed), ()))
    sched = qc = mc = factory = keeper = None
    try:
        reg = Registry()
        reg.admission = default_chain(reg)
        reg.create(t.Namespace(metadata=ObjectMeta(name="default")))
        from ..perf.gang_bench import build_slice
        build_slice(reg, 0)
        build_slice(reg, 1)
        nodes, _ = reg.list("nodes")
        for n in nodes:
            fresh = deepcopy(n)
            fresh.metadata.labels["slice"] = fresh.status.tpu.slice_id
            reg.update(fresh)
        client = LocalClient(reg)
        for obj in make_queues(nominal_chips=128.0):
            reg.create(obj)
        factory = InformerFactory(client)
        sched, qc, mc = await _migrate_stack(reg, client, factory)
        loop = asyncio.get_running_loop()
        gang_size: dict = {}
        keeper = _member_keeper(reg, client, gang_size)

        def bound_members(ns: str, gang: str) -> list:
            pods, _ = reg.list("pods", ns)
            return [p for p in pods if p.spec.gang == gang
                    and p.spec.node_name and t.is_pod_active(p)]

        # Phase 1: stage the fragmentation.
        pin, pin_pods = make_gang("pin-00", "tenant-a", "queue-a",
                                  shape=[4, 4, 2])
        await client.create(pin)
        for pod in pin_pods:
            await client.create(pod)
        await _wait(lambda: len(bound_members("tenant-a", "pin-00")) >= 8,
                    loop.time() + timeout / 3, "pin gang bound")
        don, don_pods = make_gang("don-00", "tenant-a", "queue-a",
                                  checkpoint_grace=10.0)
        for pod in don_pods:
            pod.spec.node_selector = {"slice": "slice-001"}
        await client.create(don)
        for pod in don_pods:
            await client.create(pod)
        gang_size["don-00"] = ("tenant-a", "queue-a", len(don_pods))
        await _wait(lambda: len(bound_members("tenant-a", "don-00")) >= 2,
                    loop.time() + timeout / 3, "donor gang bound")
        assert all(p.spec.node_name.startswith("slice-001")
                   for p in bound_members("tenant-a", "don-00"))

        # Phase 2: the big gang is blocked on both slices.
        big, big_pods = make_gang("big-00", "tenant-b", "queue-b",
                                  shape=[4, 4, 4])
        await client.create(big)
        for pod in big_pods:
            await client.create(pod)

        # Phase 3: defrag moves the donor; the big gang binds.
        await _wait(lambda: len(bound_members("tenant-b", "big-00")) >= 16,
                    loop.time() + timeout, "big gang bound after defrag")
        d = reg.get("podgroups", "tenant-a", "don-00")
        mig = d.status.migration
        assert mig is not None and mig.outcome == "moved", mig
        assert mig.reason == t.MIGRATE_REASON_DEFRAG, mig.reason
        assert all(p.spec.node_name.startswith("slice-000")
                   for p in bound_members("tenant-a", "don-00"))
        st = d.status.preemption
        assert st is not None and st.checkpoint_step > 0, st
        big_nodes = {p.spec.node_name
                     for p in bound_members("tenant-b", "big-00")}
        assert all(n.startswith("slice-001") for n in big_nodes)
        return {
            "donor_outcome": mig.outcome,
            "donor_reason": mig.reason,
            "donor_rounds": mig.rounds,
            "donor_checkpoint_step": st.checkpoint_step,
            "donor_bound": len(bound_members("tenant-a", "don-00")),
            "big_bound": len(bound_members("tenant-b", "big-00")),
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
    finally:
        chaos.disarm()
        if keeper is not None:
            keeper.cancel()
        if mc is not None:
            await mc.stop()
        if qc is not None:
            await qc.stop()
        if sched is not None:
            await sched.stop()
        if factory is not None:
            await factory.stop_all()
        for g, on in was.items():
            if not on:
                GATES.set(g, False)


def run_migrate_smoke_schedules(base_seed, schedules: int = 4,
                                mode: str = "dpor",
                                timeout: float = 60.0) -> dict:
    """tpusan arm of the live-migration gate: the evacuation story
    explored under ``schedules`` interleavings with the cluster
    invariants armed (incl. migration-no-strand), asserting the
    deterministic convergence facts are byte-identical on every
    schedule."""
    from ..analysis import interleave

    keys = ("outcome", "reason", "rounds", "checkpoint_step", "bound",
            "off_sick_host", "crash_faults")
    rep = interleave.explore_sanitized(
        lambda i: run_migrate_smoke(seed=int(base_seed) if str(
            base_seed).isdigit() else 0, timeout=timeout),
        base_seed=base_seed, schedules=schedules, mode=mode,
        extract=lambda v: {k: v[k] for k in keys})
    outcomes = [{k: r[k] for k in keys} for r in rep["schedules"]]
    assert all(o == outcomes[0] for o in outcomes), (
        f"convergence diverged across schedules: {outcomes}")
    rep["base_seed"] = base_seed
    return rep


def run_queue_smoke_schedules(base_seed, schedules: int = 4,
                              mode: str = "dpor",
                              timeout: float = 30.0) -> dict:
    """The tpusan arm of the queueing gate: the same two-tenant
    admission story explored under ``schedules`` seeded interleavings
    with the invariant sanitizer armed — the DRF/borrow/reclaim path
    must hold conservation and monotonicity on EVERY schedule, not just
    the one the event loop happens to produce. Raises on any scenario
    assert or invariant violation (the tpusan seed replays it)."""
    from ..analysis import interleave

    rep = interleave.explore_sanitized(
        lambda i: run_queue_smoke(timeout=timeout),
        base_seed=base_seed, schedules=schedules, mode=mode,
        extract=lambda v: {"reclaimed_gangs": v["reclaimed_gangs"]})
    rep["base_seed"] = base_seed
    return rep
