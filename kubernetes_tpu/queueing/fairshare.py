"""Fair-share admission math — pure, deterministic, side-effect free.

The QueueController's decision core, factored out of the informer/API
machinery so the invariants can be property-tested directly:

- **DRF ordering** (Dominant Resource Fairness, Ghodsi et al., applied
  per arXiv:2510.01256's tenant-quota scheduling): pending gangs are
  admitted in the order produced by repeatedly picking the queue with
  the lowest dominant share, charging the pick hypothetically, and
  repeating — so a flooding tenant's 2nd..Nth gangs queue behind every
  other tenant's 1st.
- **Cohort borrowing**: a queue may exceed its nominal quota using
  cohort-mates' idle quota, bounded per-resource by its
  ``borrowing_limit`` and by total cohort headroom (sum of usage never
  exceeds sum of nominal — the conservation invariant).
- **Reclaim pricing**: when a queue's own demand returns but borrowers
  hold its quota, victims are chosen cheapest-first with the SAME cost
  order the scheduler's gang preemption uses (``scheduler.py
  _cheaper``: max victim priority, then gang size), most recent
  admission first among equals (LIFO — the shortest-lived disruption).
- **EASY backfill**: with the head-of-line gang blocked, a later gang
  may jump iff it fits outright AND its projected completion
  (``runtime``) lands before the blocker's *shadow time* — the
  earliest instant the blocker could start given admitted gangs'
  projected completions — so the jump can never delay the blocker
  (arXiv:2010.11307's queued-admission utilization argument).

Everything here operates on plain snapshots (:class:`QueueState`,
:class:`Workload`); the controller translates API objects in and
status updates out.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from ..api.types import RESOURCE_TPU

INF = float("inf")


@dataclass
class QueueState:
    """One ClusterQueue's accounting snapshot for an admission pass."""

    name: str
    cohort: str = ""
    #: Per-resource nominal quota. Resources absent here are UNGOVERNED
    #: by this queue — demand for them is not charged (so a chips-only
    #: quota config admits cpu-carrying gangs without modelling cpu).
    nominal: dict[str, float] = field(default_factory=dict)
    #: Per-resource cap on usage beyond nominal (missing key = no cap
    #: beyond cohort headroom). Meaningless without a cohort.
    borrowing_limit: dict[str, float] = field(default_factory=dict)
    #: Admitted usage, mutated by :func:`charge` / :func:`release`.
    usage: dict[str, float] = field(default_factory=dict)

    def governed(self, demand: dict[str, float]) -> dict[str, float]:
        return {r: a for r, a in demand.items() if r in self.nominal}

    def clone(self) -> "QueueState":
        """Independent copy for hypothetical charging (DRF scratch,
        shadow replay, reclaim simulation)."""
        return QueueState(name=self.name, cohort=self.cohort,
                          nominal=dict(self.nominal),
                          borrowing_limit=dict(self.borrowing_limit),
                          usage=dict(self.usage))


@dataclass
class Workload:
    """One gang (PodGroup) from admission's point of view."""

    key: str                 # namespace/name of the PodGroup
    queue: str               # ClusterQueue name
    demand: dict[str, float] = field(default_factory=dict)
    priority: int = 0
    #: Creation stamp (seconds) — FIFO order within a queue.
    created: float = 0.0
    #: Projected runtime in seconds (annotation / activeDeadline);
    #: None = unknown, which disqualifies it from backfilling.
    runtime: Optional[float] = None
    #: Set on admitted workloads.
    admitted_at: Optional[float] = None
    mode: str = ""           # "", Nominal, Borrowed, Backfill
    #: Elastic gangs above min_replicas: the demand they would charge
    #: at min_replicas. None = not shrinkable (fixed-size, already at
    #: min, or the GracefulPreemption gate is off). Reclaim prefers
    #: shrinking such a gang (releasing demand - min_demand) over
    #: fully unadmitting anyone at the same priority.
    min_demand: Optional[dict] = None


# -- shares -----------------------------------------------------------------


def dominant_share(q: QueueState) -> float:
    """Max over governed resources of usage/nominal. A resource with
    zero nominal but positive usage dominates everything (inf)."""
    share = 0.0
    for res, cap in q.nominal.items():
        used = q.usage.get(res, 0.0)
        if used <= 0:
            continue
        share = max(share, used / cap if cap > 0 else INF)
    return share


def borrowed(q: QueueState) -> dict[str, float]:
    return {res: q.usage.get(res, 0.0) - cap
            for res, cap in q.nominal.items()
            if q.usage.get(res, 0.0) > cap}


def charge(q: QueueState, demand: dict[str, float]) -> None:
    for res, amt in q.governed(demand).items():
        q.usage[res] = q.usage.get(res, 0.0) + amt


def release(q: QueueState, demand: dict[str, float]) -> None:
    for res, amt in q.governed(demand).items():
        q.usage[res] = max(0.0, q.usage.get(res, 0.0) - amt)


def cohort_headroom(cohort_queues: list[QueueState]) -> dict[str, float]:
    """Per-resource idle capacity across the cohort: sum(nominal) -
    sum(usage), over every resource any member governs."""
    total: dict[str, float] = {}
    used: dict[str, float] = {}
    for q in cohort_queues:
        for res, cap in q.nominal.items():
            total[res] = total.get(res, 0.0) + cap
        for res, amt in q.usage.items():
            if any(res in m.nominal for m in cohort_queues):
                used[res] = used.get(res, 0.0) + amt
    return {res: cap - used.get(res, 0.0) for res, cap in total.items()}


# -- admission --------------------------------------------------------------


def admission_mode(q: QueueState, cohort_queues: list[QueueState],
                   demand: dict[str, float]) -> tuple[Optional[str], bool]:
    """Can ``demand`` be admitted into ``q`` right now?

    Returns ``(mode, needs_reclaim)``: mode is ``"Nominal"`` /
    ``"Borrowed"`` / None. ``needs_reclaim=True`` means the demand fits
    the queue's OWN nominal quota but cohort-mates have borrowed it
    away — the caller should reclaim (preempt borrowers), not reject.
    """
    gov = q.governed(demand)
    fits_nominal = all(q.usage.get(r, 0.0) + a <= q.nominal[r] + 1e-9
                       for r, a in gov.items())
    headroom = (cohort_headroom(cohort_queues) if q.cohort
                else {r: q.nominal[r] - q.usage.get(r, 0.0)
                      for r in q.nominal})
    fits_cohort = all(gov[r] <= headroom.get(r, 0.0) + 1e-9 for r in gov)
    if fits_nominal:
        return ("Nominal", False) if fits_cohort else (None, True)
    if not q.cohort:
        return None, False
    fits_borrow = all(
        q.usage.get(r, 0.0) + a
        <= q.nominal[r] + q.borrowing_limit.get(r, INF) + 1e-9
        for r, a in gov.items())
    if fits_borrow and fits_cohort:
        return "Borrowed", False
    return None, False


def structurally_admissible(q: QueueState,
                            cohort_queues: list[QueueState],
                            demand: dict[str, float]) -> bool:
    """Could ``demand`` EVER be admitted into ``q`` at current quota
    config, with the whole cohort idle? A gang failing this is
    inadmissible — it must be skipped, not allowed to become a
    permanent head-of-line blocker starving its cohort."""
    gov = q.governed(demand)
    cohort_total: dict[str, float] = {}
    for m in cohort_queues:
        for res, cap in m.nominal.items():
            cohort_total[res] = cohort_total.get(res, 0.0) + cap
    for res, amt in gov.items():
        ceiling = q.nominal[res] + (q.borrowing_limit.get(res, INF)
                                    if q.cohort else 0.0)
        ceiling = min(ceiling, cohort_total.get(res, q.nominal[res]))
        if amt > ceiling + 1e-9:
            return False
    return True


def pending_order(pending: list[Workload]) -> list[Workload]:
    """Within-queue order: priority desc, then FIFO, then name."""
    return sorted(pending, key=lambda w: (-w.priority, w.created, w.key))


def drf_order(queues: dict[str, QueueState],
              pending: list[Workload]) -> list[Workload]:
    """Global admission order across tenants.

    Deterministic and input-permutation-invariant: repeatedly pick the
    queue with the lowest (dominant_share, name), emit its head
    workload, and charge it against a SCRATCH copy of usage so each
    pick sees the shares the previous picks produced.
    """
    scratch = {name: q.clone() for name, q in queues.items()}
    remaining = {name: pending_order([w for w in pending if w.queue == name])
                 for name in queues}
    order: list[Workload] = []
    while any(remaining.values()):
        pick = min((name for name, ws in remaining.items() if ws),
                   key=lambda n: (dominant_share(scratch[n]), n))
        w = remaining[pick].pop(0)
        charge(scratch[pick], w.demand)
        order.append(w)
    return order


# -- backfill ---------------------------------------------------------------


def shadow_time(blocker: Workload, queues: dict[str, QueueState],
                admitted: list[Workload], now: float) -> float:
    """Earliest time the blocker could be admitted, replaying admitted
    gangs' projected completions (admitted_at + runtime) in order.
    Gangs with unknown runtime never complete in the replay; if the
    blocker still doesn't fit after every known completion, the shadow
    is +inf (no reservation can be computed)."""
    sim = {name: q.clone() for name, q in queues.items()}

    def fits_now() -> bool:
        q = sim.get(blocker.queue)
        if q is None:
            return False
        cohort = [m for m in sim.values() if q.cohort and m.cohort == q.cohort]
        mode, _ = admission_mode(q, cohort, blocker.demand)
        return mode is not None

    if fits_now():
        return now
    ends = sorted(
        ((max(now, w.admitted_at + w.runtime), w)
         for w in admitted
         if w.runtime is not None and w.admitted_at is not None),
        key=lambda pair: (pair[0], pair[1].key))
    for end, w in ends:
        q = sim.get(w.queue)
        if q is not None:
            release(q, w.demand)
        if fits_now():
            return end
    return INF


def backfill_ok(candidate: Workload, shadow: float, now: float) -> bool:
    """May ``candidate`` jump the blocked head? Only with a BOUNDED
    projected runtime, and only when it completes before the blocker's
    shadow time. An infinite shadow (blocker waits on unknown-runtime
    gangs) admits any bounded candidate — it cannot postpone "unknown".
    """
    if candidate.runtime is None:
        return False
    if math.isinf(shadow):
        return True
    return now + candidate.runtime <= shadow + 1e-9


# -- reclaim ----------------------------------------------------------------


def reclaim_cost(w: Workload) -> tuple:
    """Victim pricing, aligned with scheduler gang preemption's
    ``_cheaper`` (max priority, then size), then LIFO by admission."""
    return (w.priority,
            w.demand.get(RESOURCE_TPU, 0.0),
            -(w.admitted_at or 0.0),
            w.key)


#: plan_reclaim actions.
RECLAIM_SHRINK = "shrink"
RECLAIM_EVICT = "evict"


def _unit_released(w: Workload, action: str) -> dict[str, float]:
    """Demand an action frees: shrink releases the elastic delta;
    evict releases whatever the gang still charges (full demand, or
    min_demand if a shrink of the same gang was already applied —
    callers apply units in order)."""
    if action == RECLAIM_SHRINK:
        assert w.min_demand is not None
        return {r: max(0.0, a - w.min_demand.get(r, 0.0))
                for r, a in w.demand.items()}
    return dict(w.demand)


def plan_reclaim(lender: QueueState,
                 demand: dict[str, float],
                 cohort_queues: list[QueueState],
                 admitted: list[Workload]
                 ) -> list[tuple[Workload, str]]:
    """Choose reclaim actions whose releases restore enough cohort
    headroom for ``demand``. Returns [] when reclaim cannot help (the
    shortfall is not held by over-nominal queues). Victims come only
    from queues CURRENTLY over their nominal — a queue within its own
    quota is never preempted to serve a neighbor. Deliberately not
    filtered by admission-time mode: a quota shrink can push usage
    admitted as Nominal over the new nominal, and those chips must be
    reclaimable or the cohort deadlocks behind an unservable blocker.

    Elastic gangs (``min_demand`` set) are SHRUNK before anyone at the
    same priority is fully evicted — Kant's unified elasticity: a
    borrower gives back its borrowed slice sub-meshes and keeps
    training at min_replicas instead of dying. A shrunken gang may
    still be fully evicted later in the same plan (releasing its
    residual min_demand) if shrinking alone cannot cover the
    shortfall."""
    gov = lender.governed(demand)
    if not gov:
        return []
    headroom = cohort_headroom(cohort_queues)
    shortfall = {r: a - headroom.get(r, 0.0)
                 for r, a in gov.items() if a > headroom.get(r, 0.0) + 1e-9}
    if not shortfall:
        return []
    by_name = {q.name: q for q in cohort_queues}
    sim_usage = {q.name: dict(q.usage) for q in cohort_queues}

    def over_nominal(qname: str) -> dict[str, float]:
        q = by_name[qname]
        return {r: sim_usage[qname].get(r, 0.0) - cap
                for r, cap in q.nominal.items()
                if sim_usage[qname].get(r, 0.0) > cap + 1e-9}

    # Candidate units: (cost, workload, action). Same pricing as the
    # scheduler's gang preemption (priority, then released size, then
    # LIFO); at equal priority a shrink sorts before any evict — the
    # less disruptive release wins ties.
    units: list[tuple[tuple, Workload, str]] = []
    for w in admitted:
        if w.queue not in by_name:
            continue
        if w.min_demand is not None:
            delta = _unit_released(w, RECLAIM_SHRINK)
            units.append(((w.priority, 0, delta.get(RESOURCE_TPU, 0.0),
                           -(w.admitted_at or 0.0), w.key),
                          w, RECLAIM_SHRINK))
        units.append(((w.priority, 1, w.demand.get(RESOURCE_TPU, 0.0),
                       -(w.admitted_at or 0.0), w.key),
                      w, RECLAIM_EVICT))
    units.sort(key=lambda u: u[0])
    shrunk: set[str] = set()
    plan: list[tuple[Workload, str]] = []
    for _cost, w, action in units:
        if not shortfall:
            break
        released = _unit_released(w, action)
        if action == RECLAIM_EVICT and w.key in shrunk:
            # The shrink already gave back the delta; a full evict now
            # frees only the residual min-size charge.
            released = dict(w.min_demand or {})
        over = over_nominal(w.queue)
        # Only useful if its queue is over nominal in a short resource
        # AND this release actually frees some of it — else it frees
        # nothing the blocker needs (and the cost sort would put
        # exactly such zero-TPU gangs first).
        if not any(r in over and released.get(r, 0.0) > 1e-9
                   for r in shortfall):
            continue
        plan.append((w, action))
        if action == RECLAIM_SHRINK:
            shrunk.add(w.key)
        q = by_name[w.queue]
        for r, a in q.governed(released).items():
            sim_usage[w.queue][r] = max(
                0.0, sim_usage[w.queue].get(r, 0.0) - a)
        sims = []
        for m in cohort_queues:
            s = m.clone()
            s.usage = sim_usage[m.name]
            sims.append(s)
        headroom = cohort_headroom(sims)
        shortfall = {r: a - headroom.get(r, 0.0)
                     for r, a in gov.items()
                     if a > headroom.get(r, 0.0) + 1e-9}
    return plan if not shortfall else []


def pick_reclaim_victims(lender: QueueState,
                         demand: dict[str, float],
                         cohort_queues: list[QueueState],
                         admitted: list[Workload]) -> list[Workload]:
    """Evict-only view of :func:`plan_reclaim` — the pre-elastic
    interface, exactly equivalent when no workload carries
    ``min_demand``."""
    return [w for w, action in plan_reclaim(lender, demand,
                                            cohort_queues, admitted)
            if action == RECLAIM_EVICT]
